"""Native C++ span loader + table lane: equivalence with the pandas lane."""

import numpy as np
import pandas as pd
import pytest

from microrank_tpu.config import MicroRankConfig
from microrank_tpu.io import load_traces_csv
from microrank_tpu.io.naming import operation_names
from microrank_tpu.pipeline import run_rca, run_rca_native
from microrank_tpu.testing import SyntheticConfig, generate_case

native = pytest.importorskip("microrank_tpu.native")
if not native.native_available():
    pytest.skip("g++ / native build unavailable", allow_module_level=True)


@pytest.fixture(scope="module")
def csv_pair(tmp_path_factory):
    d = tmp_path_factory.mktemp("native_csv")
    case = generate_case(
        SyntheticConfig(
            n_operations=24, n_traces=200, seed=9, n_pods=2,
            n_kinds=24, child_keep_prob=0.6,
        )
    )
    case.normal.to_csv(d / "normal.csv", index=False)
    case.abnormal.to_csv(d / "abnormal.csv", index=False)
    return d, case


def test_loader_matches_pandas(csv_pair):
    d, case = csv_pair
    tab = native.load_span_table(d / "abnormal.csv")
    df = load_traces_csv(d / "abnormal.csv")
    # The loader time-sorts rows (stable, by startTime) so window seams
    # can slice searchsorted ranges — mirror it on the pandas side.
    assert tab.time_sorted
    df = df.sort_values("startTime", kind="stable").reset_index(drop=True)
    assert tab.n_spans == len(df)
    start = tab.start_us
    assert bool(np.all(start[1:] >= start[:-1]))
    assert [tab.trace_names[i] for i in tab.trace_id] == df["traceID"].tolist()
    assert [tab.svc_op_names[i] for i in tab.svc_op] == operation_names(
        df, "service"
    ).tolist()
    assert [tab.pod_op_names[i] for i in tab.pod_op] == operation_names(
        df, "pod"
    ).tolist()
    np.testing.assert_array_equal(
        tab.duration_us, df["duration"].to_numpy()
    )
    np.testing.assert_array_equal(
        tab.start_us,
        df["startTime"].astype("datetime64[us]").astype("int64").to_numpy(),
    )
    np.testing.assert_array_equal(
        tab.end_us,
        df["endTime"].astype("datetime64[us]").astype("int64").to_numpy(),
    )
    pos = {s: i for i, s in enumerate(df["spanID"])}
    exp_parent = np.array(
        [
            pos.get(p, -1) if isinstance(p, str) and p else -1
            for p in df["ParentSpanId"].fillna("")
        ],
        dtype=np.int64,
    )
    np.testing.assert_array_equal(tab.parent_row, exp_parent)


def test_loader_clickhouse_header(csv_pair, tmp_path):
    d, case = csv_pair
    raw = case.abnormal.rename(
        columns={
            "traceID": "TraceId", "spanID": "SpanId",
            "serviceName": "ServiceName", "operationName": "SpanName",
            "podName": "PodName", "duration": "Duration",
            "startTime": "TraceStart", "endTime": "TraceEnd",
        }
    )
    raw.insert(0, "Timestamp", raw["TraceStart"])
    raw["SpanKind"] = "Server"
    raw.to_csv(tmp_path / "raw.csv", index=False)
    tab = native.load_span_table(tmp_path / "raw.csv")
    ref = native.load_span_table(d / "abnormal.csv")
    assert tab.n_spans == ref.n_spans
    np.testing.assert_array_equal(tab.trace_id, ref.trace_id)
    np.testing.assert_array_equal(tab.pod_op, ref.pod_op)


def test_loader_strip_rule(tmp_path, csv_pair):
    _, case = csv_pair
    df = case.abnormal.copy()
    df.loc[df.index[:5], "serviceName"] = "ts-ui-dashboard"
    df.loc[df.index[:5], "operationName"] = "GET /api/v1/item/123"
    df.to_csv(tmp_path / "strip.csv", index=False)
    tab = native.load_span_table(tmp_path / "strip.csv")
    # Rows are time-sorted at load — find the stripped spans by name
    # presence instead of CSV position.
    assert "ts-ui-dashboard_GET /api/v1/item" in tab.svc_op_names
    stripped = tab.svc_op_names.index("ts-ui-dashboard_GET /api/v1/item")
    assert int(np.sum(tab.svc_op == stripped)) == 5


def test_loader_quoted_fields(tmp_path):
    (tmp_path / "q.csv").write_text(
        "traceID,spanID,ParentSpanId,operationName,serviceName,podName,"
        "duration,startTime,endTime\n"
        '"t1","s1","","GET /a,b","svc ""x""","pod-1",1000,'
        '"2025-02-14 12:00:00","2025-02-14 12:00:01"\n'
    )
    tab = native.load_span_table(tmp_path / "q.csv")
    assert tab.n_spans == 1
    assert tab.svc_op_names[tab.svc_op[0]] == 'svc "x"_GET /a,b'
    assert tab.start_us[0] == np.datetime64("2025-02-14 12:00:00", "us").astype(
        "int64"
    )


def test_loader_missing_file():
    with pytest.raises(ValueError, match="cannot open"):
        native.load_span_table("/nonexistent/traces.csv")


def test_loader_bad_header(tmp_path):
    (tmp_path / "bad.csv").write_text("a,b,c\n1,2,3\n")
    with pytest.raises(ValueError, match="missing required columns"):
        native.load_span_table(tmp_path / "bad.csv")


def test_table_lane_matches_pandas_lane(csv_pair):
    d, case = csv_pair
    cfg = MicroRankConfig()
    r_pandas = run_rca(
        load_traces_csv(d / "normal.csv"),
        load_traces_csv(d / "abnormal.csv"),
        cfg,
    )
    r_native = run_rca_native(d / "normal.csv", d / "abnormal.csv", cfg)
    a = next(r for r in r_pandas if r.ranking)
    b = next(r for r in r_native if r.ranking)
    assert [n for n, _ in a.ranking] == [n for n, _ in b.ranking]
    assert (a.n_normal, a.n_abnormal) == (b.n_normal, b.n_abnormal)
    assert a.ranking[0][0] == case.fault_pod_op


def _assert_graphs_equal(g1, g2):
    for side in ("normal", "abnormal"):
        a, b = getattr(g1, side), getattr(g2, side)
        for f in a._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)),
                np.asarray(getattr(b, f)),
                err_msg=f"{side}.{f}",
            )


def test_native_graph_build_matches_numpy(csv_pair):
    """C++ counting-sort builder is array-identical to the numpy lane."""
    from microrank_tpu.graph.table_ops import build_window_graph_from_table

    d, _ = csv_pair
    tab = native.load_span_table(d / "abnormal.csv")
    full = np.ones(tab.n_spans, dtype=bool)
    partial = np.arange(tab.n_spans) % 3 != 0
    for mask in (full, partial):
        codes = np.unique(tab.trace_id[mask])
        nrm, abn = codes[::2], codes[1::2]
        # aux="all" also compares the C++-exported bitmap and CSR kernel
        # views against the numpy-lane constructions, field for field.
        for aux in ("auto", "all"):
            g1, n1, a1, b1 = build_window_graph_from_table(
                tab, mask, nrm, abn, use_native=True, aux=aux
            )
            g2, n2, a2, b2 = build_window_graph_from_table(
                tab, mask, nrm, abn, use_native=False, aux=aux
            )
            assert n1 == n2
            np.testing.assert_array_equal(a1, a2)
            np.testing.assert_array_equal(b1, b2)
            _assert_graphs_equal(g1, g2)


def test_native_graph_build_empty_partition(csv_pair):
    """One empty partition must not crash and must match numpy."""
    from microrank_tpu.graph.table_ops import build_window_graph_from_table

    d, _ = csv_pair
    tab = native.load_span_table(d / "abnormal.csv")
    mask = np.ones(tab.n_spans, dtype=bool)
    codes = np.unique(tab.trace_id)
    g1, _, a1, b1 = build_window_graph_from_table(
        tab, mask, [], codes, use_native=True
    )
    g2, _, a2, b2 = build_window_graph_from_table(
        tab, mask, [], codes, use_native=False
    )
    assert len(a1) == len(a2) == 0
    np.testing.assert_array_equal(b1, b2)
    _assert_graphs_equal(g1, g2)


def test_loader_sidecar_cache(tmp_path, csv_pair):
    """Sidecar .npz reused when fresh, invalidated when the CSV changes."""
    import os
    import time as _time

    _, case = csv_pair
    p = tmp_path / "t.csv"
    case.abnormal.to_csv(p, index=False)
    a = native.load_span_table(p)
    sidecars = list(tmp_path.glob("*.npz"))
    assert len(sidecars) == 1
    b = native.load_span_table(p)  # cache hit
    np.testing.assert_array_equal(a.pod_op, b.pod_op)
    assert a.trace_names == b.trace_names
    # Stale cache: rewrite the CSV with one span fewer, bump mtime.
    case.abnormal.iloc[:-1].to_csv(p, index=False)
    os.utime(p, (_time.time() + 2, _time.time() + 2))
    c = native.load_span_table(p)
    assert c.n_spans == a.n_spans - 1


def test_pathological_input_both_lanes(tmp_path):
    """Unicode names, an orphan parent id, and a zero-duration trace flow
    through both ingest lanes; lane outputs agree and the zero-duration
    trace is dropped by the detector's valid mask (reference
    preprocess_data.py:116-117)."""
    from microrank_tpu.config import MicroRankConfig
    from microrank_tpu.detect import detect_numpy
    from microrank_tpu.graph.table_ops import (
        compute_slo_from_table,
        detect_batch_from_table,
    )
    from microrank_tpu.rank_backends import get_backend
    from microrank_tpu.testing import SyntheticConfig, generate_case
    from conftest import partition_case

    case = generate_case(
        SyntheticConfig(
            n_operations=20, n_traces=120, seed=3, n_kinds=24,
            child_keep_prob=0.6,
        )
    )
    ab = case.abnormal.copy()
    ab.loc[ab.index[:3], "serviceName"] = "svc-ünïcode-服务"
    ab.loc[ab.index[5], "ParentSpanId"] = "missing-span-xyz"
    dead_trace = ab["traceID"].iloc[0]
    ab.loc[ab["traceID"] == dead_trace, "duration"] = 0

    # Pandas lane: ranking still works with the pathological rows.
    nrm, abn = partition_case(case)
    nrm = [t for t in nrm if t != dead_trace]
    abn = [t for t in abn if t != dead_trace]
    top, _ = get_backend(MicroRankConfig()).rank_window(ab, nrm, abn)
    assert top

    # Native lane: rows, vocab, and the unicode names survive the mmap
    # scan; the zero-duration trace is invalid to the detector.
    ab.to_csv(tmp_path / "patho.csv", index=False)
    table = native.load_span_table(tmp_path / "patho.csv")
    assert table.n_spans == len(ab)
    assert any("ünïcode" in n for n in table.svc_op_names)
    nrm_t = case.normal.copy()
    nrm_t.to_csv(tmp_path / "norm.csv", index=False)
    ntab = native.load_span_table(tmp_path / "norm.csv")
    vocab, baseline = compute_slo_from_table(ntab)
    batch, codes = detect_batch_from_table(
        table, np.ones(table.n_spans, bool), vocab
    )
    det = detect_numpy(batch, baseline, MicroRankConfig().detector)
    dead_code = list(table.trace_names).index(dead_trace)
    local = list(codes).index(dead_code)
    assert not det.valid[local]


def test_edge_bitmap_and_fallback_agree(csv_pair, monkeypatch):
    """The scan-time edge-bitmap dedup and the counting-sort fallback
    (vocab past the bitmap budget) must build identical graphs; the
    chunked thread pool must match the serial path."""
    from microrank_tpu.graph.table_ops import build_window_graph_from_table

    d, _ = csv_pair
    tab = native.load_span_table(d / "abnormal.csv")
    mask = np.ones(tab.n_spans, dtype=bool)
    codes = np.unique(tab.trace_id)
    nrm, abn = codes[::2], codes[1::2]

    def build():
        g, _, a, b = build_window_graph_from_table(
            tab, mask, nrm, abn, use_native=True, aux="all"
        )
        return g, a, b

    base_g, base_a, base_b = build()
    for env in (
        {"MR_EDGE_BITMAP_MAX_VOCAB": "0"},   # force counting-sort path
        {"MR_BUILD_THREADS": "4"},            # force chunked finishing
        {"MR_EDGE_BITMAP_MAX_VOCAB": "0", "MR_BUILD_THREADS": "4"},
    ):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        g, a, b = build()
        for k in env:
            monkeypatch.delenv(k)
        np.testing.assert_array_equal(a, base_a)
        np.testing.assert_array_equal(b, base_b)
        _assert_graphs_equal(g, base_g)


def test_native_detect_matches_numpy(tmp_path):
    """The fused C++ detector must produce IDENTICAL window masks and
    normal/abnormal partitions as detect_batch_from_table + detect_numpy
    across several windows of a multi-window timeline (including an
    empty window past the end)."""
    import numpy as np

    from microrank_tpu.config import MicroRankConfig
    from microrank_tpu.detect import detect_numpy
    from microrank_tpu.detect.detector import _thresholds
    from microrank_tpu.graph.table_ops import (
        compute_slo_from_table,
        detect_batch_from_table,
        window_rows,
    )
    from microrank_tpu.native import (
        detect_window_native,
        load_span_table,
        native_available,
    )
    from microrank_tpu.testing import SyntheticConfig
    from microrank_tpu.testing.synthetic import generate_timeline

    if not native_available():
        pytest.skip("native lane unavailable")
    tl = generate_timeline(
        SyntheticConfig(n_operations=24, n_traces=120, seed=13),
        4,
        [0, 2],
    )
    tl.normal.to_csv(tmp_path / "normal.csv", index=False)
    tl.timeline.to_csv(tmp_path / "abnormal.csv", index=False)
    normal = load_span_table(tmp_path / "normal.csv")
    table = load_span_table(tmp_path / "abnormal.csv")
    cfg = MicroRankConfig()
    vocab, baseline = compute_slo_from_table(normal)
    thresh = _thresholds(baseline, cfg.detector)
    remap = vocab.encode(table.svc_op_names).astype(np.int32)

    w_us = int(tl.window_minutes * 60e6)
    start = int(tl.start.value // 1000)
    for b in range(6):  # windows 4..5 are past the end (empty)
        w0, w1 = start + b * w_us, start + (b + 1) * w_us
        n_mask, n_nrm, n_abn, n_window, n_seen = detect_window_native(
            table, w0, w1, remap, thresh, cfg.detector.slack_ms
        )
        mask = window_rows(table, w0, w1)
        np.testing.assert_array_equal(np.asarray(n_mask), mask, f"mask w{b}")
        assert n_window == int(mask.sum()), b
        if n_window == 0:
            assert len(n_nrm) == 0 and len(n_abn) == 0
            continue
        batch, codes = detect_batch_from_table(table, mask, vocab)
        det = detect_numpy(batch, baseline, cfg.detector)
        t = len(codes)
        abn = codes[det.abnormal[:t]]
        nrm = codes[det.valid[:t] & ~det.abnormal[:t]]
        np.testing.assert_array_equal(np.sort(n_abn), np.sort(abn), f"abn w{b}")
        np.testing.assert_array_equal(np.sort(n_nrm), np.sort(nrm), f"nrm w{b}")
