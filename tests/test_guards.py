"""utils/guards.py edge cases: assert_finite_scores boundary behavior and
the thread-local contract switch."""

import numpy as np
import pytest

from microrank_tpu.utils.guards import (
    ContractError,
    NumericsError,
    assert_finite_scores,
    contract_checks,
    contracts_enabled,
    set_contract_checks,
)


def test_empty_scores_pass():
    assert_finite_scores([], "empty")
    assert_finite_scores(np.zeros(0, np.float32), "empty-array")


def test_finite_scores_pass():
    assert_finite_scores([1.0, 2.5, -3.0], "ok")
    assert_finite_scores(np.arange(5, dtype=np.float32), "ok-array")


def test_all_nan_raises_with_positions():
    with pytest.raises(NumericsError, match=r"positions \[0, 1, 2\]"):
        assert_finite_scores([np.nan, np.nan, np.nan], "nan-case")


def test_inf_only_raises():
    with pytest.raises(NumericsError, match="inf"):
        assert_finite_scores([np.inf], "inf-case")
    with pytest.raises(NumericsError, match="-inf"):
        assert_finite_scores([-np.inf], "neg-inf-case")


def test_mixed_reports_first_five_bad_positions():
    scores = [0.0, np.nan, 1.0, np.inf, np.nan, np.nan, np.nan, np.nan]
    with pytest.raises(NumericsError, match=r"positions \[1, 3, 4, 5, 6\]"):
        assert_finite_scores(scores, "mixed")


def test_scalar_nan_raises():
    with pytest.raises(NumericsError):
        assert_finite_scores(np.float64("nan"), "scalar")


def test_non_array_input_raises_numerics_error():
    # A corrupted fetch should surface as a numerics failure at the
    # validation boundary, not a numpy cast error deep in the caller.
    with pytest.raises(NumericsError, match="non-numeric"):
        assert_finite_scores(["not", "numbers"], "strings")
    with pytest.raises(NumericsError, match="non-numeric"):
        assert_finite_scores(object(), "object")


def test_context_names_the_failure_site():
    with pytest.raises(NumericsError, match="JaxBackend.rank_window"):
        assert_finite_scores([np.nan], "JaxBackend.rank_window")


def test_contract_switch_defaults_off_and_restores():
    assert not contracts_enabled()
    with contract_checks(True):
        assert contracts_enabled()
        with contract_checks(False):
            assert not contracts_enabled()
        assert contracts_enabled()
    assert not contracts_enabled()


def test_contract_switch_restores_on_error():
    with pytest.raises(RuntimeError):
        with contract_checks(True):
            raise RuntimeError("boom")
    assert not contracts_enabled()


def test_set_contract_checks_imperative():
    set_contract_checks(True)
    try:
        assert contracts_enabled()
    finally:
        set_contract_checks(False)
    assert not contracts_enabled()


def test_contract_error_is_type_error():
    # Callers catching TypeError (the natural category for a signature
    # violation) see contract failures too.
    assert issubclass(ContractError, TypeError)
