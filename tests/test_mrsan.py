"""mrsan — the runtime sanitizer that cross-checks mrlint's static
model (R8 device ownership / R9 collective order).

Covers: ownership asserts at the device seams (owner passes, foreign
thread raises, authorized delegates pass, disarmed is free), per-shard
collective-schedule recording on the CPU mesh (uniform real program;
injected divergence trips), the serve degrade-path guard (satellite
bugfix), sanitized end-to-end runs staying violation-free, and the CI
cross-validation contract: the injected-bug fixtures flip BOTH the
static and the runtime detector.
"""

import json
import threading
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from conftest import partition_case
from microrank_tpu.analysis import lint_paths, mrsan
from microrank_tpu.config import (
    MicroRankConfig,
    RuntimeConfig,
    ServeConfig,
    StreamConfig,
)
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.utils.guards import (
    DeviceOwnershipError,
    assert_device_owner,
    authorize_device_thread,
    claim_device_owner,
    device_owner,
    release_device_owner,
    sanitizers_enabled,
)

DATA = Path(__file__).parent / "data" / "mrlint"


def _value(registry, name, **labels) -> float:
    """Counter value, 0.0 when the metric was never recorded."""
    m = registry.get(name)
    return 0.0 if m is None else m.value(**labels)


def _total(registry, name) -> float:
    m = registry.get(name)
    return (
        0.0
        if m is None
        else sum(smp["value"] for smp in m.samples())
    )


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture
def armed(registry):
    """Sanitizers armed process-wide for the test, disarmed after."""
    cfg = MicroRankConfig(runtime=RuntimeConfig(sanitizers=True))
    mrsan.configure_sanitizers(cfg)
    yield cfg
    mrsan.configure_sanitizers(MicroRankConfig())


def _call_in_thread(fn, *args):
    """Run fn on a fresh thread; return the exception it raised (or
    None)."""
    box = {}

    def run():
        try:
            fn(*args)
        except BaseException as e:  # noqa: BLE001 — test harness
            box["err"] = e

    t = threading.Thread(target=run, name="mrsan-foreign")
    t.start()
    t.join()
    return box.get("err")


# ----------------------------------------------------------- ownership


def test_configure_arms_and_disarms(registry):
    mrsan.configure_sanitizers(
        MicroRankConfig(runtime=RuntimeConfig(sanitizers=True))
    )
    assert sanitizers_enabled() and mrsan.armed()
    mrsan.configure_sanitizers(MicroRankConfig())
    assert not sanitizers_enabled() and not mrsan.armed()
    assert device_owner() == (None, None)


def test_owner_thread_passes_foreign_thread_raises(armed, registry):
    claim_device_owner("test-owner")
    assert_device_owner("test.seam")  # owner: fine
    err = _call_in_thread(assert_device_owner, "test.seam")
    assert isinstance(err, DeviceOwnershipError)
    assert "test.seam" in str(err) and "test-owner" in str(err)
    assert (
        _value(
            registry,
            "microrank_mrsan_violations_total",
            kind="cross-thread-device",
        )
        == 1
    )
    # Both entries counted as performed checks.
    assert (
        _value(registry, "microrank_mrsan_checks_total", seam="test.seam")
        == 2
    )


def test_authorized_delegate_passes(armed):
    claim_device_owner("test-owner")
    with ThreadPoolExecutor(
        1, "delegate", initializer=authorize_device_thread
    ) as pool:
        pool.submit(assert_device_owner, "test.seam").result()


def test_no_claim_means_no_enforcement(armed):
    release_device_owner()
    assert _call_in_thread(assert_device_owner, "test.seam") is None


def test_disarmed_checks_are_free(registry):
    mrsan.configure_sanitizers(MicroRankConfig())  # sanitizers off
    claim_device_owner("test-owner")
    try:
        assert _call_in_thread(assert_device_owner, "test.seam") is None
        assert (
            _value(
                registry, "microrank_mrsan_checks_total", seam="test.seam"
            )
            == 0
        )
    finally:
        release_device_owner()


def test_reclaim_follows_active_pipeline(armed):
    claim_device_owner("first")
    err = _call_in_thread(claim_device_owner, "second")
    assert err is None  # re-claim from the new run's thread is legal
    role, ident = device_owner()
    assert role == "second" and ident != threading.get_ident()
    with pytest.raises(DeviceOwnershipError):
        assert_device_owner("test.seam")


# ------------------------------------------- locksets & lock order


def test_tracked_lock_records_held_locksets(armed):
    from microrank_tpu.utils.guards import TrackedLock, held_locks

    a = TrackedLock("t.a")
    b = TrackedLock("t.b")
    assert held_locks() == ()
    with a:
        assert held_locks() == ("t.a",)
        with b:
            assert held_locks() == ("t.a", "t.b")
        assert held_locks() == ("t.a",)
    assert held_locks() == ()


def test_tracked_lock_disarmed_records_nothing(registry):
    from microrank_tpu import analysis
    from microrank_tpu.utils.guards import TrackedLock, held_locks

    analysis.mrsan.configure_sanitizers(MicroRankConfig())
    lock = TrackedLock("t.off")
    with lock:
        assert held_locks() == ()
    # Still a real mutex when disarmed.
    assert lock.locked() is False


def test_registered_object_foreign_access_trips(armed, registry):
    """The Eraser discipline at runtime: an access that empties the
    candidate lockset raises and counts the violation."""
    from microrank_tpu.utils.guards import (
        LocksetError,
        TrackedLock,
        note_shared_access,
        register_shared,
    )

    lock = TrackedLock("obj.lock")
    register_shared("obj", {"obj.lock"})
    with lock:
        note_shared_access("obj")  # candidates stay {obj.lock}
    with pytest.raises(LocksetError, match="'obj'"):
        note_shared_access("obj")  # no lock held -> emptied
    assert (
        _value(
            registry,
            "microrank_mrsan_violations_total",
            kind="shared-state-race",
        )
        == 1
    )
    assert (
        _value(
            registry,
            "microrank_mrsan_lockset_checks_total",
            object="obj",
        )
        == 2
    )


def test_lockset_checker_disarmed_is_noop(registry):
    from microrank_tpu import analysis
    from microrank_tpu.utils.guards import (
        note_shared_access,
        register_shared,
    )

    analysis.mrsan.configure_sanitizers(MicroRankConfig())
    register_shared("obj2", {"some.lock"})
    note_shared_access("obj2")  # no lock held, sanitizers off: free
    assert _total(registry, "microrank_mrsan_lockset_checks_total") == 0
    assert _total(registry, "microrank_mrsan_violations_total") == 0


def test_unregistered_object_access_is_ignored(armed, registry):
    from microrank_tpu.utils.guards import note_shared_access

    note_shared_access("never-registered")
    assert _total(registry, "microrank_mrsan_lockset_checks_total") == 0


def test_lock_order_watchdog_trips_on_inversion(armed, registry):
    """A-then-B established, B-then-A raises LockOrderError (mrlint
    R11's runtime twin) and counts the violation."""
    from microrank_tpu.utils.guards import LockOrderError, TrackedLock

    a = TrackedLock("w.a")
    b = TrackedLock("w.b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderError, match="w.a"):
            with a:
                pass
    assert (
        _value(
            registry,
            "microrank_mrsan_violations_total",
            kind="lock-order",
        )
        == 1
    )
    # The inverting edge was reported, not merged: the established
    # order keeps working afterwards.
    with a:
        with b:
            pass


def test_lock_order_reset_between_runs(armed):
    from microrank_tpu import analysis
    from microrank_tpu.utils.guards import TrackedLock

    a = TrackedLock("r.a")
    b = TrackedLock("r.b")
    with a:
        with b:
            pass
    analysis.mrsan.configure_sanitizers(
        MicroRankConfig(runtime=RuntimeConfig(sanitizers=True))
    )
    # Fresh run: the opposite order is legal again (no stale edges).
    with b:
        with a:
            pass


def test_reentrant_tracked_lock_reenters(armed):
    from microrank_tpu.utils.guards import TrackedLock, held_locks

    r = TrackedLock("r.lock", reentrant=True)
    with r:
        with r:
            assert held_locks() == ("r.lock", "r.lock")
    assert held_locks() == ()


# ----------------------------------------------- collective recording


def test_real_mesh_program_records_uniform_schedule(armed, registry):
    """A shard_map psum over the 8-device CPU mesh: every shard reports
    the same op multiset; the uniformity check stays silent."""
    from jax.experimental.shard_map import shard_map

    from microrank_tpu.parallel.mesh import SHARD_AXIS, single_axis_mesh

    mesh = single_axis_mesh(4)
    mrsan.reset_schedule()

    def kern(x):
        total = jax.lax.psum(x, SHARD_AXIS)
        return x / (total + 1.0)

    x = jnp.arange(8.0)
    out = jax.jit(
        shard_map(
            kern,
            mesh=mesh,
            in_specs=P(SHARD_AXIS),
            out_specs=P(SHARD_AXIS),
        )
    )(x)
    out.block_until_ready()
    assert "psum@shard" in mrsan.trace_schedule()
    sched = mrsan.collective_schedule()
    assert set(sched) == {0, 1, 2, 3}
    assert all(c == {"psum": 1} for c in sched.values())
    assert mrsan.verify_collective_uniformity() == []
    assert (
        _value(registry, "microrank_mrsan_collectives_total", op="psum")
        == 4.0
    )
    assert (
        _value(
            registry,
            "microrank_mrsan_violations_total",
            kind="collective-divergence",
        )
        == 0
    )


def test_injected_shard_divergence_trips(armed, registry):
    """The R9 runtime bug class, injected: one shard skips a psum (as a
    data-dependent branch would make it on a real multi-host mesh —
    single-controller tracing cannot produce it organically, which is
    exactly why the recording seam exists)."""
    mrsan.reset_schedule()
    mrsan._record_runtime("psum", 0)
    mrsan._record_runtime("psum", 0)
    mrsan._record_runtime("all_gather", 0)
    mrsan._record_runtime("psum", 1)
    mrsan._record_runtime("psum", 1)  # shard 1 skipped the all_gather
    violations = mrsan.verify_collective_uniformity()
    assert len(violations) == 1
    assert "shard 1" in violations[0] and "all_gather" in violations[0]
    assert (
        _value(
            registry,
            "microrank_mrsan_violations_total",
            kind="collective-divergence",
        )
        == 1
    )
    mrsan.reset_schedule()
    assert mrsan.collective_schedule() == {}


def test_verify_and_reset_clears_between_dispatches(armed):
    mrsan._record_runtime("psum", 0)
    mrsan._record_runtime("psum", 1)
    assert mrsan.verify_and_reset() == []
    assert mrsan.collective_schedule() == {}


# ------------------------------------------------ seam integration


def test_stage_seam_trips_from_foreign_thread(armed, small_case):
    """The real blob staging seam raises when entered off the owner
    thread — the runtime twin of mrlint R8 on the injected bug."""
    from microrank_tpu.graph.build import build_window_graph
    from microrank_tpu.rank_backends.blob import stage_rank_window
    from microrank_tpu.rank_backends.jax_tpu import device_subset

    cfg = MicroRankConfig()
    nrm, abn = partition_case(small_case)
    graph, names, _, _ = build_window_graph(
        small_case.abnormal, nrm, abn, aux="none"
    )
    graph = device_subset(graph, "coo")
    claim_device_owner("test-owner")

    def dispatch():
        return stage_rank_window(
            graph, cfg.pagerank, cfg.spectrum, "coo", False
        )

    err = _call_in_thread(dispatch)
    assert isinstance(err, DeviceOwnershipError)
    assert "blob.stage_rank_window" in str(err)
    # Same call on the owner thread goes through to the device.
    handles = dispatch()
    assert handles is not None


def test_both_detectors_flip_on_injected_cross_thread_jax(
    armed, small_case
):
    """The CI contract, cross-thread half: the webhook-thread jax call
    fires R8 statically AND DeviceOwnershipError at runtime."""
    fired = {
        v.rule
        for v in lint_paths([str(DATA / "R8" / "bad_webhook_sink_fetch.py")])
    }
    assert "R8" in fired
    claim_device_owner("engine")
    scores = jnp.arange(4.0)

    def webhook_emit():
        # The fixture's bug, executed: fetch on the sink thread.
        assert_device_owner("dispatch.rank_batch")
        return jax.device_get(scores)

    err = _call_in_thread(webhook_emit)
    assert isinstance(err, DeviceOwnershipError)


def test_both_detectors_flip_on_divergent_psum(armed, registry):
    """The CI contract, collective half: the shard-divergent psum fires
    R9 statically AND the uniformity check at runtime."""
    fired = {
        v.rule
        for v in lint_paths([str(DATA / "R9" / "bad_psum_tainted_branch.py")])
    }
    assert "R9" in fired
    mrsan.reset_schedule()
    mrsan._record_runtime("psum", 0)  # shard 0 took the branch
    # shard 1 skipped it — nothing recorded
    mrsan._record_runtime("all_gather", 0)
    mrsan._record_runtime("all_gather", 1)
    assert mrsan.verify_collective_uniformity() != []


# ---------------------------------------------------- e2e: stream/serve


def test_stream_run_sanitized_stays_clean(registry, tmp_path):
    """Repo lints clean <=> a sanitized run observes zero violations:
    the runtime half, on a real gated stream run."""
    from microrank_tpu.stream import StreamEngine, SyntheticSource
    from microrank_tpu.testing import SyntheticConfig

    src = SyntheticSource(
        n_windows=4,
        faulted=[2],
        synth_config=SyntheticConfig(
            n_operations=16, n_traces=120, n_kinds=8, seed=5
        ),
        pace_seconds=0.01,
        sleep=lambda s: None,
    )
    cfg = MicroRankConfig(
        runtime=RuntimeConfig(sanitizers=True),
        stream=StreamConfig(allowed_lateness_seconds=5.0),
    )
    try:
        eng = StreamEngine(cfg, src, out_dir=tmp_path)
        s = eng.run()
    finally:
        mrsan.configure_sanitizers(MicroRankConfig())
    assert s.windows == 4 and s.ranked == 1
    assert _total(registry, "microrank_mrsan_checks_total") > 0
    # The mrrace runtime half looked too: registered shared objects
    # (build pool accounting at minimum) were lockset-checked, and
    # nothing tripped.
    assert _total(registry, "microrank_mrsan_lockset_checks_total") > 0
    assert _total(registry, "microrank_mrsan_violations_total") == 0
    # The engine thread claimed; the snapshot proves the seams looked.
    prom = (tmp_path / "metrics.prom").read_text()
    assert "microrank_mrsan_checks_total" in prom


def test_serve_degrade_path_guarded_and_clean(registry):
    """Satellite bugfix: the per-member numpy_ref fallback runs on the
    scheduler (owner) thread behind assert_device_owner — a sanitized
    degraded run completes with zero violations and the serve.degrade
    seam check counted."""
    import urllib.request

    from microrank_tpu.serve import ServeHandle, ServeService
    from microrank_tpu.testing import SyntheticConfig, generate_case

    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    df = case.abnormal.copy()
    df["startTime"] = df["startTime"].astype(str)
    df["endTime"] = df["endTime"].astype(str)
    payload = {"spans": df.to_dict("records")}
    cfg = MicroRankConfig(
        runtime=RuntimeConfig(sanitizers=True),
        serve=ServeConfig(
            warmup=False,
            max_wait_ms=100.0,
            max_batch_windows=1,
            inject_dispatch_failures=2,
        ),
    )
    svc = ServeService(cfg, out_dir=None)
    svc.fit_baseline(case.normal)
    svc.start()
    handle = ServeHandle(svc)
    port = handle.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/rank",
            data=json.dumps(payload).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            body = json.loads(r.read())
        assert body["degraded"] is True
        assert body["kernel"] == "numpy_ref"
    finally:
        handle.stop()
        mrsan.configure_sanitizers(MicroRankConfig())
    assert (
        _value(
            registry, "microrank_mrsan_checks_total", seam="serve.degrade"
        )
        >= 1
    )
    # Serve-path shared objects (admission counter, shape buckets)
    # were lockset-checked by the armed run.
    assert (
        _value(
            registry,
            "microrank_mrsan_lockset_checks_total",
            object="serve_admission",
        )
        >= 1
    )
    assert _total(registry, "microrank_mrsan_violations_total") == 0
