"""Sparse full-scale oracle (rank_backends.sparse_oracle) vs the dense
oracle: same window, same partitions -> same ranked names and
near-identical float64 scores. The sparse oracle exists to verify the
device path at sizes the dense [V, T] matrices can't reach, so IT must
first be proven against the dense oracle where both run.
"""

import numpy as np
import pytest

from conftest import partition_case
from microrank_tpu.config import MicroRankConfig, SpectrumConfig
from microrank_tpu.graph import build_window_graph
from microrank_tpu.rank_backends import NumpyRefBackend
from microrank_tpu.rank_backends.sparse_oracle import rank_window_sparse
from microrank_tpu.testing import SyntheticConfig, generate_case


def _compare(case, cfg):
    nrm, abn = partition_case(case)
    top_d, sc_d = NumpyRefBackend(cfg).rank_window(case.abnormal, nrm, abn)
    graph, op_names, _, _ = build_window_graph(case.abnormal, nrm, abn)
    top_s, sc_s = rank_window_sparse(
        graph, op_names, cfg.pagerank, cfg.spectrum
    )
    assert top_d, "dense oracle produced no ranking"
    # The dense oracle's default tiebreak is "name", matching the sparse
    # oracle's (-score, name) sort — so the full ranked lists must agree
    # positionally, not just as sets.
    assert top_s == top_d
    # Both float64; the residual difference is pure summation-order
    # reassociation (bincount entry order vs dense BLAS column order).
    np.testing.assert_allclose(sc_s, sc_d, rtol=1e-6)


def test_sparse_matches_dense_default(small_case):
    _compare(small_case, MicroRankConfig())


def test_sparse_matches_dense_pod_level(pod_case):
    _compare(pod_case, MicroRankConfig())


@pytest.mark.parametrize("method", ["ochiai", "tarantula", "dstar2"])
def test_sparse_matches_dense_methods(small_case, method):
    _compare(
        small_case,
        MicroRankConfig(spectrum=SpectrumConfig(method=method)),
    )


def test_sparse_matches_dense_paper_preference(small_case):
    from microrank_tpu.config import PageRankConfig

    _compare(
        small_case,
        MicroRankConfig(pagerank=PageRankConfig(preference="paper")),
    )


def test_sparse_oracle_duplicate_span_traces():
    # Kind dedup must separate traces with the same unique op set but
    # different with-duplicate lengths (the p_sr column VALUE differs) —
    # a regression guard for the byte-signature grouping.
    case = generate_case(
        SyntheticConfig(
            n_operations=16, n_traces=150, seed=5, child_keep_prob=0.9
        )
    )
    _compare(case, MicroRankConfig())


def test_fuzz_full_ranking_parity_vs_jax():
    # Property-style sweep: random medium-scale workloads, full-ranking
    # tie-aware parity between the device path and the float64 sparse
    # oracle (the check bench runs at 1M spans, here across topology
    # space). Catches kernel/oracle drift no single fixture would.
    import jax
    import jax.numpy as jnp

    from microrank_tpu.rank_backends.jax_tpu import (
        choose_kernel,
        rank_window_device,
    )

    rng = np.random.default_rng(7)
    cfg = MicroRankConfig()
    checked = 0
    for trial in range(8):
        scfg = SyntheticConfig(
            n_operations=int(rng.integers(30, 300)),
            n_traces=int(rng.integers(150, 1200)),
            n_kinds=int(rng.integers(16, 64)),
            child_keep_prob=float(rng.uniform(0.2, 0.7)),
            seed=int(rng.integers(0, 10_000)),
        )
        case = generate_case(scfg)
        nrm, abn = partition_case(case)
        if not (nrm and abn):
            continue
        graph, op_names, _, _ = build_window_graph(case.abnormal, nrm, abn)
        kernel = choose_kernel(graph)
        ti, ts, nv = rank_window_device(
            jax.tree.map(jnp.asarray, graph),
            cfg.pagerank,
            cfg.spectrum,
            None,
            kernel,
        )
        names_j = [op_names[int(i)] for i in np.asarray(ti)[: int(nv)]]
        scores_j = [float(s) for s in np.asarray(ts)[: int(nv)]]
        top_o, sc_o = rank_window_sparse(
            graph, op_names, cfg.pagerank, cfg.spectrum
        )
        # Top-1 exactly; top-5 via the SAME tie-aware comparator the
        # bench oracle gate uses (f32 device vs f64 oracle ties).
        from microrank_tpu.utils.ranking_compare import (
            tie_aware_topk_agreement,
        )

        assert names_j and names_j[0] == top_o[0], (scfg, names_j[:3], top_o[:3])
        ok, why = tie_aware_topk_agreement(
            names_j, scores_j, top_o, sc_o, k=5, rtol=2e-3
        )
        assert ok, (scfg, why, names_j[:5], top_o[:5])
        checked += 1
    assert checked >= 5
