"""Multi-host distribution (component C19): a REAL two-process CPU mesh.

The worker (two_process_rank_worker.py) joins a jax.distributed runtime
(Gloo collectives on CPU — the same initialize + mesh + shard_map path a
TPU pod uses over ICI/DCN), forms one global (2, 4) mesh across both
processes' 4 local devices each, and ranks the same four windows the
single-process sharded tests use. Both processes must produce the full
batch result, equal to the single-process ranking.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import partition_case
from microrank_tpu.config import MicroRankConfig
from microrank_tpu.graph import build_window_graph
from microrank_tpu.parallel import (
    make_mesh,
    rank_windows_sharded,
    stack_window_graphs,
)
from microrank_tpu.testing import SyntheticConfig, generate_case

_WORKER = Path(__file__).parent / "two_process_rank_worker.py"


def test_initialize_is_noop_without_config():
    # No coordinator/env configured -> no side effects, False.
    from microrank_tpu.parallel.distributed import initialize_distributed

    assert initialize_distributed() is False
    assert jax.process_count() == 1


def test_global_put_single_process_equals_device_put():
    # global_put on a single-process mesh is a sharded device_put.
    from microrank_tpu.graph.structures import WindowGraph
    from microrank_tpu.parallel.distributed import global_put
    from microrank_tpu.parallel.sharded_rank import (
        SHARD_AXIS,
        WINDOW_AXIS,
        _partition_specs,
    )

    case = generate_case(SyntheticConfig(n_operations=20, n_traces=100, seed=1))
    nrm, abn = partition_case(case)
    graph, _, _, _ = build_window_graph(case.abnormal, nrm, abn)
    stacked = stack_window_graphs([graph, graph], shard_multiple=4)
    mesh = make_mesh((2, 4))
    pspecs = _partition_specs(WINDOW_AXIS, SHARD_AXIS)
    specs = WindowGraph(normal=pspecs, abnormal=pspecs)
    put = global_put(stacked, mesh, specs)
    for a, b in zip(jax.tree.leaves(put), jax.tree.leaves(stacked)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.skipif(
    os.environ.get("MICRORANK_SKIP_MULTIPROCESS") == "1",
    reason="multi-process test disabled",
)
def test_two_process_mesh_ranks_like_single_process(tmp_path):
    # Capability gate (ROADMAP open item): cross-process CPU collectives
    # need the Gloo transport; jaxlibs without it raise "Multiprocess
    # computations aren't implemented on the CPU backend" inside the
    # workers' psums. initialize_distributed selects Gloo when the probe
    # passes, so on capable jaxlibs this test runs for real.
    from microrank_tpu.parallel.distributed import cpu_collectives_supported

    if not cpu_collectives_supported():
        pytest.skip(
            "this jaxlib lacks CPU Gloo collectives "
            "(make_gloo_tcp_collectives); cross-process CPU psums "
            "cannot run"
        )
    # Expected result: the in-process (2, 4) sharded ranking.
    cfg = MicroRankConfig()
    graphs = []
    for seed in (1, 2, 3, 4):
        case = generate_case(
            SyntheticConfig(n_operations=20, n_traces=100, seed=seed)
        )
        nrm, abn = partition_case(case)
        graph, _, _, _ = build_window_graph(case.abnormal, nrm, abn)
        graphs.append(graph)
    mesh = make_mesh((2, 4))
    stacked = stack_window_graphs(graphs, shard_multiple=4)
    sti, sts, snv = rank_windows_sharded(
        jax.tree.map(jnp.asarray, stacked), cfg.pagerank, cfg.spectrum, mesh
    )
    expected_idx = np.asarray(sti)
    expected_scores = np.asarray(sts, np.float64)
    expected_nv = np.asarray(snv)

    # Shared tables for the full-pipeline (TableRCA) leg of the worker.
    pytest.importorskip("microrank_tpu.native")
    from microrank_tpu.native import load_span_table, native_available
    from microrank_tpu.pipeline import TableRCA
    from microrank_tpu.config import RuntimeConfig

    table_dir = None
    expected_table = None
    if native_available():
        tcase = generate_case(
            SyntheticConfig(n_operations=20, n_traces=120, seed=5,
                            n_kinds=24, child_keep_prob=0.6)
        )
        table_dir = tmp_path / "tables"
        table_dir.mkdir()
        tcase.normal.to_csv(table_dir / "n.csv", index=False)
        tcase.abnormal.to_csv(table_dir / "a.csv", index=False)
        single = TableRCA(
            MicroRankConfig(runtime=RuntimeConfig(mesh_shape=(8,)))
        )
        single.fit_baseline(load_span_table(table_dir / "n.csv"))
        expected_table = [
            [[n, float(s)] for n, s in r.ranking] if r.ranking else None
            for r in single.run(load_span_table(table_dir / "a.csv"))
        ]

    # Two real processes, 4 virtual CPU devices each, one Gloo runtime.
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    procs = []
    outs = []
    for pid in (0, 1):
        out = tmp_path / f"worker_{pid}.json"
        outs.append(out)
        env = {
            **os.environ,
            "PYTHONPATH": str(Path(__file__).parent.parent),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "MICRORANK_COORDINATOR": f"localhost:{port}",
            "MICRORANK_NUM_PROCESSES": "2",
            "MICRORANK_PROCESS_ID": str(pid),
        }
        cmd = [sys.executable, str(_WORKER), str(out)]
        if table_dir is not None:
            cmd.append(str(table_dir))
        procs.append(
            subprocess.Popen(
                cmd,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    logs = [p.communicate(timeout=240)[0] for p in procs]
    for p, log_text in zip(procs, logs):
        assert p.returncode == 0, log_text[-2000:]

    from microrank_tpu.utils.ranking_compare import tie_aware_topk_agreement

    dumps = [json.loads(out.read_text()) for out in outs]
    # The two processes see the SAME allgathered result, bit-identical —
    # they ran one collective program.
    assert dumps[0]["top_idx"] == dumps[1]["top_idx"]
    assert dumps[0]["top_scores"] == dumps[1]["top_scores"]
    assert dumps[0].get("table_rankings") == dumps[1].get("table_rankings")
    for pid, res in enumerate(dumps):
        assert res["process_index"] == pid
        assert res["is_primary"] == (pid == 0)
        # Versus the single-process sharded ranking: the cross-process
        # Gloo reduction tree may legally reassociate f32 sums, so
        # near-exact ties can permute — the shared tie-aware comparator
        # (bench/multichip gate semantics) decides agreement.
        np.testing.assert_array_equal(np.asarray(res["n_valid"]), expected_nv)
        for w in range(expected_idx.shape[0]):
            nv = int(expected_nv[w])
            got_idx = res["top_idx"][w][:nv]
            got_scores = res["top_scores"][w][:nv]
            ok, reason = tie_aware_topk_agreement(
                expected_idx[w][:nv].tolist(),
                expected_scores[w][:nv].tolist(),
                got_idx,
                got_scores,
                k=nv,
                rtol=1e-3,
            )
            assert ok, f"window {w}: {reason}"
        # The full TableRCA pipeline over the process-spanning mesh must
        # agree with the single-process (1, 8) mesh the same way.
        if expected_table is not None:
            got_table = res["table_rankings"]
            assert len(got_table) == len(expected_table)
            for w, (exp, got) in enumerate(zip(expected_table, got_table)):
                if exp is None or got is None:
                    assert exp == got, f"table window {w}"
                    continue
                ok, reason = tie_aware_topk_agreement(
                    [n for n, _ in exp], [s for _, s in exp],
                    [n for n, _ in got], [s for _, s in got],
                    k=len(exp), rtol=1e-3,
                )
                assert ok, f"table window {w}: {reason}"


def test_initialize_partial_config_falls_back(monkeypatch):
    # A leftover MICRORANK_NUM_PROCESSES without a coordinator must warn
    # and keep the single-process fallback, not raise inside jax.
    from microrank_tpu.parallel.distributed import initialize_distributed

    monkeypatch.setenv("MICRORANK_NUM_PROCESSES", "2")
    monkeypatch.delenv("MICRORANK_COORDINATOR", raising=False)
    monkeypatch.delenv("MICRORANK_PROCESS_ID", raising=False)
    assert initialize_distributed() is False
    assert jax.process_count() == 1
