"""Incremental ranking (ISSUE 20): delta-build + fused pair program.

Covers the tentpole's exactness contract and its guards: delta-vs-cold
parity across kernels x collapse x blob staging (tie-aware identical
ranking at convergence), the cold-fallback guard chain (churn,
integrity, vocab, params, bounds) counted in
microrank_build_route_total, warm-start invalidation across a
kind-collapse column-map change (a stale-state dispatch can never flip
a tie-aware top-k verdict), the fused pair program's single-dispatch
parity, and the stream engine wiring end to end.
"""

import dataclasses

import jax
import numpy as np
import pandas as pd
import pytest

from microrank_tpu.config import MicroRankConfig, PageRankConfig
from microrank_tpu.graph.build import (
    build_window_graph,
    build_window_graph_delta,
)
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.utils.ranking_compare import tie_aware_topk_agreement

CFG = MicroRankConfig()
W_US = 100_000_000        # 100 s window
S_US = 25_000_000         # 25 s slide -> 75% overlap


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


def _timeline(n_traces=160, seed=1, n_ops=6, span_us=None):
    """Synthetic span timeline with temporally compact traces (each
    trace's spans sit in a 2 s band) so a 75% slide keeps most traces
    intact. Every op name appears throughout the timeline, keeping the
    window vocab stable (the delta lane's frozen-vocab contract)."""
    rng = np.random.default_rng(seed)
    span = span_us if span_us is not None else W_US + 4 * S_US
    rows = []
    base = np.sort(rng.integers(0, span - 2_000_000, size=n_traces))
    for i in range(n_traces):
        tid = f"tr{seed}_{i}"
        n = int(rng.integers(3, 8))
        t_us = base[i] + np.sort(rng.integers(0, 2_000_000, size=n))
        sids = [f"{tid}_s{j}" for j in range(n)]
        for j in range(n):
            svc = f"svc{rng.integers(0, 5)}"
            rows.append(
                {
                    "traceID": tid,
                    "spanID": sids[j],
                    "ParentSpanId": sids[j - 1] if j else "",
                    "serviceName": svc,
                    "operationName": f"op{rng.integers(0, n_ops)}",
                    "podName": svc + "-pod0",
                    "startTime": pd.Timestamp(
                        int(t_us[j]) * 1000, unit="ns"
                    ),
                    "duration": int(rng.integers(1, 100)),
                }
            )
    return pd.DataFrame(rows)


def _window(frames, lo_us, hi_us):
    t = frames["startTime"].to_numpy().view("int64") // 1000
    return frames[(t >= lo_us) & (t < hi_us)].reset_index(drop=True)


def _partition(frame):
    tids = sorted(frame["traceID"].unique())
    return tids[: len(tids) // 2], tids[len(tids) // 2 :]


def _slide(frames, k):
    lo = k * S_US
    wf = _window(frames, lo, lo + W_US)
    nrm, abn = _partition(wf)
    return wf, nrm, abn, lo, lo + W_US


def _names_scores(out, names):
    n = int(out[2])
    return (
        [names[int(i)] for i in np.asarray(out[0])[:n]],
        [float(s) for s in np.asarray(out[1])[:n]],
    )


# ------------------------------------------------- delta-vs-cold parity


PARITY_MATRIX = [
    # (kernel, collapse, blob) — every kernel, both collapse modes,
    # blob staging alternated so both staging paths rank delta graphs.
    ("kind", "on", True),
    ("kind", "off", False),
    ("packed", "off", True),
    ("packed", "on", False),
    ("pcsr", "off", False),
    ("pcsr", "on", True),
    ("coo", "off", True),
    ("coo", "on", False),
    ("csr", "off", False),
    ("csr", "on", True),
]


@pytest.mark.parametrize("kernel,collapse,blob", PARITY_MATRIX)
def test_delta_vs_cold_ranking_parity(kernel, collapse, blob):
    """A delta-route window must rank tie-aware-identical to the cold
    build of the same frame, through the actual device program for
    every kernel family, collapsed and uncollapsed, both staging
    paths."""
    from microrank_tpu.graph.build import aux_for_kernel
    from microrank_tpu.rank_backends.blob import stage_rank_window
    from microrank_tpu.rank_backends.jax_tpu import device_subset

    frames = _timeline(seed=3)
    aux = aux_for_kernel(kernel)
    state = None
    saw_delta = False
    pr = dataclasses.replace(CFG.pagerank, iterations=15)
    for k in range(3):
        wf, nrm, abn, lo, hi = _slide(frames, k)
        res = build_window_graph_delta(
            wf, nrm, abn, state=state, start_us=lo, end_us=hi,
            aux=aux, collapse=collapse,
        )
        state = res.state
        if res.route != "delta":
            continue
        saw_delta = True
        cold = build_window_graph(
            wf, nrm, abn, aux=aux, collapse=collapse
        )
        out_d = jax.device_get(
            stage_rank_window(
                device_subset(res.graph, kernel), pr, CFG.spectrum,
                kernel, blob,
            )
        )
        out_c = jax.device_get(
            stage_rank_window(
                device_subset(cold[0], kernel), pr, CFG.spectrum,
                kernel, blob,
            )
        )
        names_d, scores_d = _names_scores(out_d, res.op_names)
        names_c, scores_c = _names_scores(out_c, cold[1])
        ok, why = tie_aware_topk_agreement(
            names_d, scores_d, names_c, scores_c,
            k=min(5, len(names_c)), rtol=1e-6,
        )
        assert ok, f"window {k}: {why}"
    assert saw_delta, "no window took the delta route"


def test_delta_graph_statistics_match_cold_exactly():
    """Value-level parity of the assembled partitions: the delta graph's
    incidence/edge statistics (the sr/rs/ss weights the kernels consume)
    must be exactly the cold build's, as sets — numbering may differ
    only through the frozen superset vocab."""
    frames = _timeline(seed=4)
    state = None
    checked = 0
    # min_pad=512 pins every pad bucket (counts stay below it), so the
    # no-recompile pad-signature guard never forces a cold rebuild and
    # each slide past the first exercises the delta assembly.
    for k in range(4):
        wf, nrm, abn, lo, hi = _slide(frames, k)
        res = build_window_graph_delta(
            wf, nrm, abn, state=state, start_us=lo, end_us=hi,
            min_pad=512,
        )
        state = res.state
        if res.route != "delta":
            continue
        checked += 1
        g_cold, ops_cold, i0, i1 = build_window_graph(
            wf, nrm, abn, min_pad=512
        )
        for part_d, part_c in (
            (res.graph.normal, g_cold.normal),
            (res.graph.abnormal, g_cold.abnormal),
        ):
            ops_d = res.op_names
            nnz = int(np.count_nonzero(np.asarray(part_d.sr_val)))
            assert nnz == int(np.count_nonzero(np.asarray(part_c.sr_val)))
            inc_d = sorted(
                (ops_d[int(o)], float(s), float(r))
                for o, s, r in zip(
                    part_d.inc_op[:nnz], part_d.sr_val[:nnz],
                    part_d.rs_val[:nnz],
                )
            )
            inc_c = sorted(
                (ops_cold[int(o)], float(s), float(r))
                for o, s, r in zip(
                    part_c.inc_op[:nnz], part_c.sr_val[:nnz],
                    part_c.rs_val[:nnz],
                )
            )
            assert inc_d == inc_c
            assert int(part_d.n_ops) == int(part_c.n_ops)
            assert int(part_d.n_traces) == int(part_c.n_traces)
        assert sorted(map(str, res.normal_trace_ids)) == sorted(
            map(str, i0)
        )
        assert sorted(map(str, res.abnormal_trace_ids)) == sorted(
            map(str, i1)
        )
    assert checked >= 2


# -------------------------------------------------- fallback guard chain


def test_full_turnover_forces_cold_fallback(registry):
    """Adversarial churn: a window sharing ZERO traces with the previous
    one must route cold (reason 'churn'), and both routes land in
    microrank_build_route_total."""
    from microrank_tpu.obs.metrics import record_build_route

    frames = _timeline(seed=5, span_us=3 * W_US)
    w0 = _window(frames, 0, W_US)
    # 100% turnover: same bounds overlap contract, disjoint span set.
    w1 = _window(frames, W_US, 2 * W_US)
    n0, a0 = _partition(w0)
    n1, a1 = _partition(w1)
    r0 = build_window_graph_delta(w0, n0, a0, start_us=0, end_us=W_US)
    record_build_route(r0.route)
    r1 = build_window_graph_delta(
        w1, n1, a1, state=r0.state, start_us=W_US, end_us=2 * W_US
    )
    record_build_route(r1.route)
    assert r0.route == "cold" and r0.reason == "init"
    assert r1.route == "cold" and r1.reason == "churn"
    ctr = registry.get("microrank_build_route_total")
    assert ctr.value(route="cold") == 2
    # And a clean slide of the same stream takes the delta route.
    frames2 = _timeline(seed=6)
    state = None
    for k in range(2):
        wf, nrm, abn, lo, hi = _slide(frames2, k)
        res = build_window_graph_delta(
            wf, nrm, abn, state=state, start_us=lo, end_us=hi,
            min_pad=512,
        )
        record_build_route(res.route)
        state = res.state
    assert ctr.value(route="delta") == 1


def test_guard_chain_reasons():
    """Each eligibility guard names its fallback: params mismatch,
    non-overlapping bounds, an unseen op name (frozen vocab), and an
    integrity-checksum mismatch (a late span smuggled into the cached
    region) all rebuild cold — never a wrong delta graph."""
    frames = _timeline(seed=7)
    w0, n0, a0, lo0, hi0 = _slide(frames, 0)
    r0 = build_window_graph_delta(w0, n0, a0, start_us=lo0, end_us=hi0)
    w1, n1, a1, lo1, hi1 = _slide(frames, 1)

    r = build_window_graph_delta(
        w1, n1, a1, state=r0.state, start_us=lo1, end_us=hi1, min_pad=16
    )
    assert (r.route, r.reason) == ("cold", "params")

    r = build_window_graph_delta(
        w1, n1, a1, state=r0.state, start_us=hi0 + S_US,
        end_us=hi0 + S_US + W_US,
    )
    assert (r.route, r.reason) == ("cold", "bounds")

    unseen = w1.copy()
    unseen.loc[unseen.index[-1], "operationName"] = "brand_new_op"
    r = build_window_graph_delta(
        unseen, n1, a1, state=r0.state, start_us=lo1, end_us=hi1
    )
    assert (r.route, r.reason) == ("cold", "vocab")

    # Late span: a row inside the previous window's time range that the
    # previous frame never contained — only the checksum can see it.
    late = w1.copy()
    extra = late.iloc[[0]].copy()
    tid = extra.iloc[0]["traceID"]
    extra["spanID"] = "late_span_xyz"
    extra["ParentSpanId"] = ""
    extra["startTime"] = extra["startTime"] - pd.Timedelta(seconds=1)
    late = pd.concat([late, extra], ignore_index=True)
    r = build_window_graph_delta(
        late, n1, a1, state=r0.state, start_us=lo1, end_us=hi1
    )
    assert r.route == "cold" and r.reason == "integrity", (
        r.route, r.reason, tid,
    )


def test_delta_state_ineligible_on_bad_timestamps():
    frames = _timeline(seed=8)
    w0, n0, a0, lo, hi = _slide(frames, 0)
    w0 = w0.copy()
    w0["startTime"] = np.arange(len(w0))  # not datetime64
    r = build_window_graph_delta(w0, n0, a0, start_us=lo, end_us=hi)
    assert r.route == "cold"
    assert not r.state.eligible and r.state.reason == "timestamps"


# --------------------------------- warm-start invalidation (satellite 2)


def test_warm_state_survives_column_map_change():
    """Regression pin: when the delta build changes the kind-collapse
    column map between windows (trace membership shifts, groups merge or
    split), the threaded warm state must be REMAPPED through the new
    retention map or dropped — a stale-state dispatch can never flip the
    tie-aware top-k verdict vs a cold solve of the same window."""
    from microrank_tpu.explain.bundle import ExplainContext
    from microrank_tpu.rank_backends.jax_tpu import (
        device_subset,
        rank_window_warm_device,
    )
    from microrank_tpu.rank_backends.warm import (
        capture_warm_state,
        map_warm_state,
    )

    frames = _timeline(seed=9, n_traces=200)
    pr = dataclasses.replace(CFG.pagerank, tol=1e-4, iterations=50)

    def run(graph, init):
        return jax.device_get(
            rank_window_warm_device(
                device_subset(graph, "kind"), init, pr, CFG.spectrum,
                "kind",
            )
        )

    state = None
    warm = None
    cmaps = []
    checked = 0
    for k in range(4):
        wf, nrm, abn, lo, hi = _slide(frames, k)
        res = build_window_graph_delta(
            wf, nrm, abn, state=state, start_us=lo, end_us=hi,
            aux="kind", collapse="on",
        )
        state = res.state
        ectx = ExplainContext.from_build(
            res.graph, res.normal_trace_ids, res.abnormal_trace_ids,
            res.column_map[0], res.column_map[1],
        )
        cmaps.append(
            tuple(
                None if m is None else tuple(np.asarray(m).tolist())
                for m in res.column_map
            )
        )
        init = (
            map_warm_state(warm, res.op_names, ectx, res.graph)
            if warm is not None
            else None
        )
        out_w = run(res.graph, init)
        out_c = run(res.graph, None)
        if init is not None:
            checked += 1
            ok, why = tie_aware_topk_agreement(
                *_names_scores(out_w, res.op_names),
                *_names_scores(out_c, res.op_names),
                k=5, rtol=1e-3, exempt_last=True,
            )
            assert ok, f"window {k} (stale-state flip): {why}"
        warm = capture_warm_state(res.op_names, ectx, out_w[5:9])
    assert checked >= 2
    # The pin is only meaningful if the column map actually changed
    # between consecutive windows at least once.
    assert any(a != b for a, b in zip(cmaps, cmaps[1:])), (
        "column map never changed — the invalidation path went untested"
    )


# ------------------------------------------------------ fused pair (blob)


def test_fused_pair_program_matches_separate_dispatch():
    """The fused pair program (one dispatch: both solves + epilogue)
    must reproduce the separate traced program's ranking and iteration
    telemetry, blob-staged and tree-staged."""
    from microrank_tpu.rank_backends.blob import (
        stage_rank_window,
        stage_rank_window_warm,
    )
    from microrank_tpu.rank_backends.jax_tpu import device_subset

    from microrank_tpu.graph.build import aux_for_kernel

    frames = _timeline(seed=10)
    wf, nrm, abn, _, _ = _slide(frames, 0)
    graph, names, _, _ = build_window_graph(
        wf, nrm, abn, aux=aux_for_kernel("coo")
    )
    g = device_subset(graph, "coo")
    pr = dataclasses.replace(CFG.pagerank, iterations=15)
    for blob in (True, False):
        fused = jax.device_get(
            stage_rank_window_warm(g, None, pr, CFG.spectrum, "coo", blob)
        )
        sep = jax.device_get(
            stage_rank_window(
                g, pr, CFG.spectrum, "coo", blob, conv_trace=True
            )
        )
        assert len(fused) == 9  # 5 ranked outputs + 4 state exports
        ok, why = tie_aware_topk_agreement(
            *_names_scores(fused, names), *_names_scores(sep, names),
            k=5, rtol=1e-6,
        )
        assert ok, why
        assert int(fused[4]) == int(sep[4])  # same iteration count
        # State exports carry the partition shapes for the next window.
        assert fused[5].shape == fused[7].shape  # score vectors [V]


def test_router_rank_fused_route_metrics(registry):
    """DispatchRouter.rank_fused: one dispatch, host outputs, route
    'fused' recorded in the dispatch metrics."""
    from microrank_tpu.rank_backends.jax_tpu import prepare_window_graph

    frames = _timeline(seed=11)
    wf, nrm, abn, _, _ = _slide(frames, 0)
    cfg = CFG.replace(
        pagerank=PageRankConfig(iterations=15),
    )
    graph, names, kernel = prepare_window_graph(wf, nrm, abn, cfg)
    from microrank_tpu.dispatch import DispatchRouter

    router = DispatchRouter(cfg)
    outs, info = router.rank_fused(graph, kernel, None)
    assert info.route == "fused" and info.windows == 1
    assert router.dispatches == 1
    names_f, scores_f = _names_scores(outs, names)
    assert names_f and all(np.isfinite(scores_f))
    assert registry.get(
        "microrank_dispatch_route_total"
    ).value(route="fused") == 1


# --------------------------------------------------- stream engine wiring


@pytest.mark.slow
def test_stream_engine_delta_fused_end_to_end(tmp_path):
    """Engine wiring: a sliding synthetic replay under
    delta_build+fused_pair takes the delta route on at least half the
    built windows, every ranked window dispatches through the fused
    program, and verdicts match a cold-only control engine tie-aware."""
    import json

    from microrank_tpu.config import StreamConfig, WindowConfig
    from microrank_tpu.stream import StreamEngine, SyntheticSource
    from microrank_tpu.testing import SyntheticConfig

    def source():
        return SyntheticSource(
            n_windows=6,
            faulted=[2, 3, 4],
            synth_config=SyntheticConfig(
                n_operations=24, n_traces=200, n_kinds=16, seed=5
            ),
            pace_seconds=0.01,
            sleep=lambda s: None,
        )

    def run(delta, out):
        cfg = MicroRankConfig(
            stream=StreamConfig(
                allowed_lateness_seconds=5.0, slide_minutes=1.25,
                max_windows=20,
            ),
            window=WindowConfig(detect_minutes=5.0),
        )
        cfg = cfg.replace(
            runtime=dataclasses.replace(
                cfg.runtime, delta_build=delta, fused_pair=delta
            ),
        )
        eng = StreamEngine(cfg, source(), out_dir=str(out))
        s = eng.run()
        evts = [
            json.loads(line)
            for line in (out / "journal.jsonl").read_text().splitlines()
        ]
        return s, evts

    s_delta, ev_delta = run(True, tmp_path / "delta")
    s_cold, ev_cold = run(False, tmp_path / "cold")
    routes = [
        (e["route"], e["reason"])
        for e in ev_delta
        if e["event"] == "build_route"
    ]
    n_delta = sum(1 for r, _ in routes if r == "delta")
    assert routes and n_delta >= len(routes) / 2, routes

    def ranked(evts):
        return [
            e
            for e in evts
            if e["event"] == "window" and e.get("outcome") == "ranked"
        ]

    rd, rc = ranked(ev_delta), ranked(ev_cold)
    assert len(rd) == len(rc) > 0
    assert all(e["route"] in ("fused", "fused_cold") for e in rd)
    for d, c in zip(rd, rc):
        assert d["start"] == c["start"]
        assert d["top1"] == c["top1"]
