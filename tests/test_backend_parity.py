"""THE critical suite (SURVEY.md §4 item 2): jax backend vs numpy oracle.

Same window, same partitions -> identical Top-1, same op sets, close
scores. Rank parity (not bitwise score equality) is the acceptance
criterion: the oracle iterates in float64, the device path in float32.
"""

import numpy as np
import pytest

from microrank_tpu.config import (
    MicroRankConfig,
    PageRankConfig,
    SpectrumConfig,
)
from conftest import partition_case
from microrank_tpu.rank_backends import NumpyRefBackend, get_backend
from microrank_tpu.testing import SyntheticConfig, generate_case


def _compare(case, cfg, score_rtol=1e-3):
    import dataclasses

    # Score-tolerance comparisons pin the f32 kernel: the default
    # prefer_bf16 auto kernel moves scores within bf16 rounding (~1e-3
    # relative), which is rank-stable (covered by the bf16 parity tests
    # below) but outside this suite's tight score_rtol.
    cfg = cfg.replace(
        runtime=dataclasses.replace(cfg.runtime, prefer_bf16=False)
    )
    nrm, abn = partition_case(case)
    top_o, sc_o = NumpyRefBackend(cfg).rank_window(case.abnormal, nrm, abn)
    top_j, sc_j = get_backend(cfg).rank_window(case.abnormal, nrm, abn)
    assert top_o, "oracle produced no ranking"
    # Top-1 parity: the BASELINE.json acceptance metric.
    assert top_o[0] == top_j[0]
    # Same candidate sets.
    assert set(top_o) == set(top_j)
    # Scores close, position by position after name alignment.
    scores_o = dict(zip(top_o, sc_o))
    scores_j = dict(zip(top_j, sc_j))
    for name in top_o:
        denom = max(abs(scores_o[name]), 1e-12)
        assert abs(scores_o[name] - scores_j[name]) / denom < score_rtol, name
    return top_o, top_j


def test_parity_default_config(small_case):
    top_o, _ = _compare(small_case, MicroRankConfig())
    assert top_o[0] == small_case.fault_pod_op


def test_parity_pod_level(pod_case):
    top_o, _ = _compare(pod_case, MicroRankConfig())
    # Instance-level RCA: the faulty (pod, op) outranks its sibling pod.
    sibling = pod_case.fault_pod_op.replace(
        f"-{pod_case.fault_pod}_", f"-{1 - pod_case.fault_pod}_"
    )
    assert top_o.index(pod_case.fault_pod_op) < (
        top_o.index(sibling) if sibling in top_o else len(top_o)
    )


@pytest.mark.parametrize("method", ["ochiai", "tarantula", "russellrao", "jaccard"])
def test_parity_other_spectra(small_case, method):
    cfg = MicroRankConfig(spectrum=SpectrumConfig(method=method))
    _compare(small_case, cfg)


def test_parity_paper_preference(small_case):
    cfg = MicroRankConfig(pagerank=PageRankConfig(preference="paper"))
    _compare(small_case, cfg)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_parity_across_seeds(seed):
    case = generate_case(
        SyntheticConfig(n_operations=20, n_traces=100, seed=seed)
    )
    nrm, abn = partition_case(case)
    if not (nrm and abn):
        pytest.skip("window did not partition")
    _compare(case, MicroRankConfig())


def test_top1_is_injected_fault_across_seeds():
    # Integration acceptance (SURVEY.md §4 item 3): the injected root cause
    # ranks Top-1 in most cases, Top-3 always.
    hits_top1 = 0
    total = 0
    for seed in range(5):
        # Diverse trace shapes decorrelate op coverage; with few shapes the
        # fault's always-co-occurring ancestors tie with it on the spectrum
        # counters (inherent to the algorithm — the paper's own R@1 is 94%).
        case = generate_case(
            SyntheticConfig(
                n_operations=20,
                n_traces=120,
                seed=100 + seed,
                n_kinds=24,
                child_keep_prob=0.6,
            )
        )
        nrm, abn = partition_case(case)
        if not (nrm and abn):
            continue
        cfg = MicroRankConfig()
        top, _ = get_backend(cfg).rank_window(case.abnormal, nrm, abn)
        total += 1
        assert case.fault_pod_op in top[:3], (seed, top[:5])
        hits_top1 += top[0] == case.fault_pod_op
    assert total >= 3
    assert hits_top1 >= total - 1


def test_dense_kernel_matches_coo(small_case):
    # The MXU dense path and the COO segment-sum path are the same math.
    import jax
    import jax.numpy as jnp

    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.rank_backends.jax_tpu import rank_window_device

    cfg = MicroRankConfig()
    nrm, abn = partition_case(small_case)
    graph, names, _, _ = build_window_graph(small_case.abnormal, nrm, abn)
    dg = jax.tree.map(jnp.asarray, graph)
    ti_c, ts_c, _ = rank_window_device(dg, cfg.pagerank, cfg.spectrum, None, "coo")
    ti_d, ts_d, _ = rank_window_device(dg, cfg.pagerank, cfg.spectrum, None, "dense")
    np.testing.assert_array_equal(np.asarray(ti_c), np.asarray(ti_d))
    fin = np.isfinite(np.asarray(ts_c))
    np.testing.assert_allclose(
        np.asarray(ts_c)[fin], np.asarray(ts_d)[fin], rtol=1e-4
    )


@pytest.mark.parametrize("kernel", ["csr", "packed", "packed_bf16"])
def test_scatterfree_kernels_match_coo(small_case, kernel):
    # The cumsum-difference CSR path and the bitmap-expanded packed path
    # are the same math as the COO segment-sum path (f32 reassociation
    # tolerance; bf16 matrices still carry exact 0/1 entries but round the
    # scaled vectors, so only rank order is asserted there).
    import jax
    import jax.numpy as jnp

    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.rank_backends.jax_tpu import rank_window_device

    cfg = MicroRankConfig()
    nrm, abn = partition_case(small_case)
    graph, names, _, _ = build_window_graph(
        small_case.abnormal, nrm, abn, aux="all"
    )
    dg = jax.tree.map(jnp.asarray, graph)
    ti_c, ts_c, _ = rank_window_device(dg, cfg.pagerank, cfg.spectrum, None, "coo")
    ti_k, ts_k, _ = rank_window_device(dg, cfg.pagerank, cfg.spectrum, None, kernel)
    ti_c, ts_c = np.asarray(ti_c), np.asarray(ts_c)
    ti_k, ts_k = np.asarray(ti_k), np.asarray(ts_k)
    # Top-1 parity plus same candidate set; exact positional equality is
    # not guaranteed — different summation trees perturb tied scores.
    # The candidate-set comparison excludes entries whose score ties the
    # truncation boundary: a near-tie straddling the top-k cut can
    # legally swap which op makes the list (same rule as decisive()
    # below).
    assert ti_c[0] == ti_k[0]
    rtol_cut = 2e-2 if kernel == "packed_bf16" else 1e-4

    def _decided(ti, ts):
        fin = ts[np.isfinite(ts)]
        cut = fin.min() if fin.size else 0.0
        return {
            int(i)
            for i, s in zip(ti.tolist(), ts.tolist())
            if np.isfinite(s)
            and abs(s - cut) > rtol_cut * max(abs(cut), 1e-12)
        }

    assert _decided(ti_c, ts_c) == _decided(ti_k, ts_k)
    if kernel != "packed_bf16":
        sc_c = dict(zip(ti_c.tolist(), ts_c.tolist()))
        sc_k = dict(zip(ti_k.tolist(), ts_k.tolist()))
        # Score closeness over the ops BOTH truncated lists kept (a
        # boundary-tied op can legally appear in only one).
        for op in set(sc_c) & set(sc_k):
            v = sc_c[op]
            if np.isfinite(v):
                assert abs(v - sc_k[op]) <= 1e-4 * max(abs(v), 1e-12), op


@pytest.mark.parametrize("v", [8, 13, 64, 130])
def test_pack_edge_bits_matches_host_packing(v):
    # The device-side scatter-packed call-edge bitmap must be byte-
    # identical to the host packbits path for any vocab size (including
    # non-multiples of 8) and any padding tail.
    import jax.numpy as jnp

    from microrank_tpu.graph.build import _scatter_bits
    from microrank_tpu.rank_backends.jax_tpu import pack_edge_bits

    rng = np.random.default_rng(v)
    n_edges = min(v * 3, v * v // 2)
    pairs = rng.choice(v * v, size=n_edges, replace=False)
    child = (pairs // v).astype(np.int32)
    parent = (pairs % v).astype(np.int32)
    host = _scatter_bits(child, parent, v, v)
    c_pad = n_edges + 5  # padded tail entries at index (0, 0), value 0
    device = pack_edge_bits(
        jnp.asarray(np.pad(child, (0, 5))),
        jnp.asarray(np.pad(parent, (0, 5))),
        jnp.int32(n_edges),
        v,
    )
    np.testing.assert_array_equal(host, np.asarray(device))


@pytest.mark.parametrize("ss_stage", ["edges", "bits"])
def test_packed_ss_staging_profiles_identical(small_case, ss_stage):
    # ss_stage="edges" ships the edge list and packs the bitmap on device;
    # "bits" ships the host-packed bitmap. Same uint8 array either way, so
    # rankings AND scores must be bit-identical between the profiles.
    import jax

    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.rank_backends.jax_tpu import (
        device_subset,
        rank_window_device,
    )

    cfg = MicroRankConfig()
    nrm, abn = partition_case(small_case)
    graph, names, _, _ = build_window_graph(
        small_case.abnormal, nrm, abn, aux="packed"
    )
    outs = {}
    for stage in ("edges", "bits"):
        sub = device_subset(graph, "packed", ss_stage=stage)
        if stage == "edges":
            assert sub.normal.ss_bits.shape[-1] == 0
            assert sub.normal.ss_child.shape[-1] > 0
        else:
            assert sub.normal.ss_bits.shape[-1] > 0
            assert sub.normal.ss_child.shape[-1] == 0
        outs[stage] = jax.device_get(
            rank_window_device(
                jax.device_put(sub), cfg.pagerank, cfg.spectrum, None,
                "packed",
            )
        )
    ti_e, ts_e, nv_e = outs["edges"]
    ti_b, ts_b, nv_b = outs[ss_stage]
    np.testing.assert_array_equal(ti_e, ti_b)
    np.testing.assert_array_equal(ts_e, ts_b)
    assert int(nv_e) == int(nv_b)


def test_convergence_tolerance(small_case):
    # tol-based early exit: a tight tolerance with a high iteration cap
    # must agree with the reference's fixed 25 iterations on Top-1 (the
    # iteration is convergent here), and tol=inf stops after one step yet
    # still returns finite scores.
    from microrank_tpu.config import PageRankConfig

    nrm, abn = partition_case(small_case)
    base = MicroRankConfig()
    top_ref, _ = get_backend(base).rank_window(small_case.abnormal, nrm, abn)
    tight = MicroRankConfig(
        pagerank=PageRankConfig(iterations=200, tol=1e-7)
    )
    top_tight, _ = get_backend(tight).rank_window(
        small_case.abnormal, nrm, abn
    )
    assert top_tight[0] == top_ref[0]
    # The numpy oracle honors the same tol semantics.
    top_oracle, _ = NumpyRefBackend(tight).rank_window(
        small_case.abnormal, nrm, abn
    )
    assert top_oracle[0] == top_tight[0]
    loose = MicroRankConfig(pagerank=PageRankConfig(tol=float("inf")))
    top_loose, sc_loose = get_backend(loose).rank_window(
        small_case.abnormal, nrm, abn
    )
    assert top_loose and all(np.isfinite(s) for s in sc_loose)


def test_all_methods_matches_per_method(small_case):
    # One all-formulas dispatch == 13 per-method runs.
    from microrank_tpu.spectrum.formulas import METHODS

    cfg = MicroRankConfig()
    nrm, abn = partition_case(small_case)
    backend = get_backend(cfg)
    all_out = backend.rank_window_all_methods(small_case.abnormal, nrm, abn)
    assert set(all_out) == set(METHODS)
    for method in ("dstar2", "ochiai", "tarantula", "russellrao"):
        mcfg = MicroRankConfig(spectrum=SpectrumConfig(method=method))
        names, scores = get_backend(mcfg).rank_window(
            small_case.abnormal, nrm, abn
        )
        a_names, a_scores = all_out[method]
        assert a_names[0] == names[0], method
        assert set(a_names) == set(names), method
        for n_, s_ in zip(names, scores):
            i = a_names.index(n_)
            assert a_scores[i] == pytest.approx(s_, rel=1e-5), (method, n_)


def test_forced_csr_kernel_via_config(small_case):
    # RuntimeConfig.kernel="csr" must work end to end: the backend plumbs
    # the matching aux mode into the graph build (regression: it used to
    # build aux="auto", skip the CSR views, and crash).
    from microrank_tpu.config import RuntimeConfig

    cfg = MicroRankConfig(runtime=RuntimeConfig(kernel="csr"))
    nrm, abn = partition_case(small_case)
    top, _ = get_backend(cfg).rank_window(small_case.abnormal, nrm, abn)
    assert top[0] == small_case.fault_pod_op


def test_auto_policy_past_budget_is_coherent(small_case):
    # A dense budget too small for the bitmaps must yield a
    # partition-centric-view build AND a pcsr kernel choice — build
    # policy and kernel choice cannot disagree (regression:
    # choose_kernel could pick a kernel for views that weren't built
    # and crash).
    import jax
    import jax.numpy as jnp

    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.rank_backends.jax_tpu import (
        choose_kernel,
        rank_window_device,
    )

    cfg = MicroRankConfig()
    nrm, abn = partition_case(small_case)
    graph, names, _, _ = build_window_graph(
        small_case.abnormal, nrm, abn, dense_budget_bytes=1
    )
    assert graph.normal.cov_bits.shape[1] == 0
    assert graph.normal.pc_trace.shape[-1] > 0
    kernel = choose_kernel(graph)
    assert kernel == "pcsr"
    ti, _, _ = rank_window_device(
        jax.tree.map(jnp.asarray, graph),
        cfg.pagerank,
        cfg.spectrum,
        None,
        kernel,
    )
    assert names[int(np.asarray(ti)[0])] == small_case.fault_pod_op


def test_csr_kernel_raises_without_aux(small_case):
    # aux="auto" inside the bitmap budget skips the CSR views; forcing
    # kernel="csr" must fail loudly, not return garbage.
    import jax
    import jax.numpy as jnp

    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.rank_backends.jax_tpu import rank_window_device

    cfg = MicroRankConfig()
    nrm, abn = partition_case(small_case)
    graph, _, _, _ = build_window_graph(small_case.abnormal, nrm, abn)
    assert graph.normal.inc_indptr_op.shape[0] == 0
    dg = jax.tree.map(jnp.asarray, graph)
    with pytest.raises(ValueError, match="csr"):
        rank_window_device(dg, cfg.pagerank, cfg.spectrum, None, "csr")


def test_pallas_kernel_matches_coo(small_case):
    # One-hot MXU SpMV (interpret mode on CPU) == segment-sum path.
    import jax
    import jax.numpy as jnp

    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.rank_backends.jax_tpu import rank_window_device

    cfg = MicroRankConfig()
    nrm, abn = partition_case(small_case)
    graph, names, _, _ = build_window_graph(small_case.abnormal, nrm, abn)
    dg = jax.tree.map(jnp.asarray, graph)
    ti_c, ts_c, _ = rank_window_device(dg, cfg.pagerank, cfg.spectrum, None, "coo")
    ti_p, ts_p, _ = rank_window_device(dg, cfg.pagerank, cfg.spectrum, None, "pallas")
    np.testing.assert_array_equal(np.asarray(ti_c), np.asarray(ti_p))
    fin = np.isfinite(np.asarray(ts_c))
    np.testing.assert_allclose(
        np.asarray(ts_c)[fin], np.asarray(ts_p)[fin], rtol=1e-4
    )


def test_tie_order_pinned_across_backends():
    # Tarantula-style saturation: ops that appear ONLY in abnormal traces
    # and cover every abnormal trace all score exactly 1/(1+0.5) = 2/3
    # (ef/(ef+nf)=1 exactly; eps/(eps+eps)=0.5 exactly), independent of
    # their PageRank weight. The pinned tie key — ascending op name, via
    # the name-sorted vocab index on device — must produce the SAME
    # positional ranking in the float64 oracle and every device kernel.
    import pandas as pd

    from microrank_tpu.config import RuntimeConfig

    rows = []
    sid = 0

    def trace(tid, ops):
        nonlocal sid
        root_sid = f"s{sid}"
        for i, op in enumerate(ops):
            rows.append(
                dict(
                    traceID=tid,
                    spanID=f"s{sid}",
                    ParentSpanId="missing" if i == 0 else root_sid,
                    operationName=op,
                    serviceName="svc",
                    podName="svc-0",
                    duration=1000,
                    startTime=0,
                    endTime=10,
                )
            )
            sid += 1

    nrm = [f"n{i}" for i in range(4)]
    abn = [f"a{i}" for i in range(4)]
    for tid in nrm:
        trace(tid, ["root"])
    for tid in abn:
        # Deliberately non-sorted insertion order to catch any
        # insertion-order accident surviving in either backend.
        trace(tid, ["root", "tie-b", "tie-a", "tie-c"])
    df = pd.DataFrame(rows)

    cfg = MicroRankConfig(spectrum=SpectrumConfig(method="tarantula"))
    expected_ties = ["svc-0_tie-a", "svc-0_tie-b", "svc-0_tie-c"]

    top_o, sc_o = NumpyRefBackend(cfg).rank_window(df, nrm, abn)
    assert top_o[:3] == expected_ties
    assert sc_o[0] == sc_o[1] == sc_o[2]
    for kernel in ("auto", "coo", "csr", "packed", "dense"):
        kcfg = cfg.replace(runtime=RuntimeConfig(kernel=kernel))
        top_j, _ = get_backend(kcfg).rank_window(df, nrm, abn)
        assert top_j == top_o, kernel


def test_fuzz_parity_tie_aware():
    # Randomized windows across sizes/pads/kernels: the device Top-1 must
    # be an op the float64 oracle scores within 1e-6 relative of ITS top
    # score. Exact Top-1 string equality is too strict — ops with
    # identical coverage tie to ~1e-11 relative (same ambiguity in the
    # reference), and f32 reassociation breaks such ties arbitrarily.
    import jax
    import jax.numpy as jnp

    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.rank_backends.jax_tpu import rank_window_device
    from microrank_tpu.testing import SyntheticConfig, generate_case

    cfg = MicroRankConfig()
    runs = 0
    for seed in range(8):
        rng = np.random.default_rng
        n_ops = int(rng(seed).integers(8, 60))
        n_tr = int(rng(seed + 1000).integers(40, 300))
        n_kinds = int(rng(seed + 2000).integers(4, 32))
        case = generate_case(
            SyntheticConfig(
                n_operations=n_ops, n_traces=n_tr, n_kinds=n_kinds,
                child_keep_prob=0.6, seed=seed, n_pods=1 + seed % 2,
            )
        )
        nrm, abn = partition_case(case)
        if not (nrm and abn):
            continue
        top_o, sc_o = NumpyRefBackend(cfg).rank_window(
            case.abnormal, nrm, abn
        )
        best = sc_o[0]
        near_top = {
            n for n, s in zip(top_o, sc_o)
            if abs(s - best) <= 1e-6 * max(abs(best), 1e-12)
        }
        # "exact" padding forces a fresh jit compile per window shape —
        # cover it on two seeds, pow2 (bucketed, cached) on all.
        pads = ("pow2", "exact") if seed < 2 else ("pow2",)
        # Positions whose oracle score is separated from BOTH neighbors
        # by >1e-4 relative are numerically decisive: every device
        # kernel must reproduce them POSITIONALLY, not just up to ties.
        def decisive(i):
            # The last kept position of a TRUNCATED list is never
            # decisive: its below-boundary neighbor was cut off, and a
            # near-tie straddling the cut can legally swap across it.
            if (
                i == len(sc_o) - 1
                and len(sc_o) >= cfg.spectrum.n_rows
            ):
                return False
            s = sc_o[i]
            for j in (i - 1, i + 1):
                if 0 <= j < len(sc_o):
                    if abs(s - sc_o[j]) <= 1e-4 * max(abs(s), 1e-12):
                        return False
            return True

        decisive_pos = [i for i in range(len(top_o)) if decisive(i)]
        for pad in pads:
            graph, names, _, _ = build_window_graph(
                case.abnormal, nrm, abn, pad_policy=pad, aux="all"
            )
            for kernel in ("coo", "csr", "packed", "dense"):
                runs += 1
                ti, _, _ = rank_window_device(
                    jax.tree.map(jnp.asarray, graph),
                    cfg.pagerank, cfg.spectrum, None, kernel,
                )
                ti = np.asarray(ti)
                top_j = names[int(ti[0])]
                assert top_j in near_top, (seed, pad, kernel, top_j, top_o[:3])
                for i in decisive_pos:
                    assert names[int(ti[i])] == top_o[i], (
                        seed, pad, kernel, i, names[int(ti[i])], top_o[i],
                    )
    assert runs >= 32


def test_packed_blocked_matches_packed(small_case):
    # The at-scale blocked kernel is the packed kernel with the bitmap's
    # column axis streamed through a lax.scan — same math, different
    # accumulation grouping. Force several blocks with a tiny
    # packed_block_bytes and compare against the unblocked kernel.
    import dataclasses

    import jax
    import jax.numpy as jnp

    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.rank_backends.jax_tpu import rank_window_device

    cfg = MicroRankConfig()
    nrm, abn = partition_case(small_case)
    graph, names, _, _ = build_window_graph(
        small_case.abnormal, nrm, abn, aux="packed"
    )
    t_pad = graph.abnormal.kind.shape[0]
    v_pad = graph.abnormal.cov_unique.shape[0]
    # Cap so one block holds at most a quarter of the columns.
    pr_blocked = dataclasses.replace(
        cfg.pagerank, packed_block_bytes=v_pad * (t_pad // 4) * 4
    )
    dg = jax.tree.map(jnp.asarray, graph)
    ti_p, ts_p, nv_p = rank_window_device(
        dg, cfg.pagerank, cfg.spectrum, None, "packed"
    )
    ti_b, ts_b, nv_b = rank_window_device(
        dg, pr_blocked, cfg.spectrum, None, "packed_blocked"
    )
    ti_p, ts_p = np.asarray(ti_p), np.asarray(ts_p)
    ti_b, ts_b = np.asarray(ti_b), np.asarray(ts_b)
    assert int(nv_p) == int(nv_b)
    assert ti_p[0] == ti_b[0]
    assert set(ti_p.tolist()) == set(ti_b.tolist())
    sc_p = dict(zip(ti_p.tolist(), ts_p.tolist()))
    sc_b = dict(zip(ti_b.tolist(), ts_b.tolist()))
    for op, v in sc_p.items():
        if np.isfinite(v):
            assert abs(v - sc_b[op]) <= 1e-4 * max(abs(v), 1e-12), op


def test_auto_policy_blocked_past_budget(small_case):
    # Past the dense budget the auto policy must still build bitmaps and
    # pick packed_blocked (not the ~90x slower csr), as long as the
    # bitmaps themselves fit a quarter of the budget.
    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.graph.build import (
        packed_bits_bytes,
        packed_unpacked_bytes,
        resolve_aux,
    )
    from microrank_tpu.rank_backends.jax_tpu import choose_kernel

    nrm, abn = partition_case(small_case)
    graph, _, _, _ = build_window_graph(small_case.abnormal, nrm, abn)
    v_pad = graph.normal.cov_unique.shape[0]
    t_pads = (graph.normal.kind.shape[0], graph.abnormal.kind.shape[0])
    unpacked = packed_unpacked_bytes(v_pad, t_pads)
    bits = packed_bits_bytes(v_pad, t_pads)
    # A budget between the bitmap footprint and the unpacked footprint:
    # aux still packs, kernel choice degrades to blocked.
    budget = unpacked - 1
    assert bits * 4 <= budget
    assert resolve_aux("auto", v_pad, t_pads, budget) == "packed"
    assert choose_kernel(graph, budget) == "packed_blocked"
    assert choose_kernel(graph, unpacked) == "packed"
