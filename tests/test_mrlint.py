"""mrlint suite: per-rule fixture corpus, suppression semantics, the
repo-tree cleanliness gate, and the trace-time contract checker.

Fixture layout: tests/data/mrlint/<RULE>/bad_*.py must fire <RULE>;
good_*.py must not. The tree gate is the PR invariant the CLI enforces
(`python -m microrank_tpu.cli lint microrank_tpu/` exits 0).
"""

from pathlib import Path

import numpy as np
import pytest

from microrank_tpu.analysis import RULES, lint_paths, lint_source

DATA = Path(__file__).parent / "data" / "mrlint"
REPO_PKG = Path(__file__).parent.parent / "microrank_tpu"

_FIXTURES = sorted(
    (rule_dir.name, f)
    for rule_dir in DATA.iterdir()
    if rule_dir.is_dir()
    for f in rule_dir.glob("*.py")
)


def _rules_fired(path: Path):
    return {v.rule for v in lint_paths([str(path)])}


def test_rule_catalog_complete():
    assert set(RULES) == {
        "R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9",
        "R10", "R11", "R12", "R13", "R14", "R15", "R16",
    }
    for rule in RULES.values():
        assert rule.slug and rule.summary


def test_every_rule_has_positive_and_negative_fixtures():
    by_rule = {}
    for rule, f in _FIXTURES:
        by_rule.setdefault(rule, set()).add(f.name.split("_")[0])
    for rule in RULES:
        assert by_rule.get(rule) == {"bad", "good"}, (
            f"{rule} needs at least one bad_* and one good_* fixture"
        )


@pytest.mark.parametrize(
    "rule,path",
    [(r, f) for r, f in _FIXTURES],
    ids=[f"{r}-{f.stem}" for r, f in _FIXTURES],
)
def test_fixture(rule, path):
    fired = _rules_fired(path)
    if path.name.startswith("bad_"):
        assert rule in fired, f"{path.name} should trigger {rule}"
    else:
        assert rule not in fired, f"{path.name} should not trigger {rule}"


def test_repo_tree_is_clean():
    """The PR invariant: the package lints clean (violations are fixed
    or carry a justified # mrlint: disable=...)."""
    violations = lint_paths([str(REPO_PKG)])
    assert violations == [], "\n".join(v.format() for v in violations)


def test_cli_lint_exits_zero_on_clean(capsys):
    # The CLI's zero-exit/"clean" contract on a clean subtree. The
    # full-tree cleanliness invariant is test_repo_tree_is_clean above
    # (same lint_paths engine) — re-linting the whole package through
    # the CLI doubled the most expensive call in the suite for no
    # added coverage.
    from microrank_tpu.cli.main import main

    assert main(["lint", str(REPO_PKG / "utils")]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_lint_exits_nonzero_on_bad(capsys):
    from microrank_tpu.cli.main import main

    bad = DATA / "R3" / "bad_tracer_branch.py"
    assert main(["lint", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "R3" in out and "finding" in out


_BAD_SNIPPET = """\
import jax


def f(x):
    return x * float(x)
{pragma}

f_jit = jax.jit(f)
"""


def test_disable_with_reason_suppresses():
    src = _BAD_SNIPPET.format(pragma="").replace(
        "return x * float(x)",
        "return x * float(x)  # mrlint: disable=R1(fixture: known sync)",
    )
    assert all(v.rule != "R1" for v in lint_source(src))


def test_disable_on_preceding_line_suppresses():
    src = _BAD_SNIPPET.format(pragma="").replace(
        "    return x * float(x)",
        "    # mrlint: disable=R1(fixture: known sync)\n"
        "    return x * float(x)",
    )
    assert all(v.rule != "R1" for v in lint_source(src))


def test_bare_disable_reported_as_r0():
    src = _BAD_SNIPPET.format(pragma="").replace(
        "return x * float(x)",
        "return x * float(x)  # mrlint: disable=R1",
    )
    rules = {v.rule for v in lint_source(src)}
    assert "R0" in rules and "R1" not in rules


def test_wrong_rule_disable_does_not_suppress():
    src = _BAD_SNIPPET.format(pragma="").replace(
        "return x * float(x)",
        "return x * float(x)  # mrlint: disable=R2(wrong rule)",
    )
    assert "R1" in {v.rule for v in lint_source(src)}


# ----------------------------------------------- framework edge cases (v2)


_DECORATED_SNIPPET = """\
import jax


def wrap(f):
    return f


@wrap
def f(x):
{body}


f_jit = jax.jit(f)
"""


def test_disable_inside_decorated_def_suppresses():
    """A comment-line pragma guards the next line even when the def it
    lives in is decorated (decorators shift the def's lineno story —
    the suppression must anchor to the violating line, not the def)."""
    src = _DECORATED_SNIPPET.format(
        body=(
            "    # mrlint: disable=R1(fixture: justified sync)\n"
            "    return x * float(x)"
        )
    )
    assert all(v.rule != "R1" for v in lint_source(src))


def test_disable_on_decorator_line_does_not_leak_into_body():
    """An end-of-line pragma guards ITS line only: parked on the
    decorator it must not swallow a violation inside the body."""
    src = _DECORATED_SNIPPET.format(
        body="    return x * float(x)"
    ).replace(
        "@wrap", "@wrap  # mrlint: disable=R1(wrong line: decorator)"
    )
    assert "R1" in {v.rule for v in lint_source(src)}


def test_r0_counting_matched_and_floating_bare_disables():
    """Suppression counting: a bare disable that matches a finding
    converts it to exactly one R0; a floating bare disable adds exactly
    one more — no double counting from the two emission paths."""
    src = _BAD_SNIPPET.format(pragma="").replace(
        "return x * float(x)",
        "return x * float(x)  # mrlint: disable=R1",
    ) + "\n# mrlint: disable=R4\n"
    vs = lint_source(src)
    assert [v.rule for v in vs].count("R0") == 2
    assert "R1" not in {v.rule for v in vs}


def test_r0_not_duplicated_for_multiple_findings_on_one_line():
    """Two findings suppressed by one justified pragma line stay
    suppressed; the same line bare produces R0 per emission, deduped by
    line in the floating sweep."""
    src = """\
import jax


def f(x):
    return float(x) + float(x)  # mrlint: disable=R1(fixture: double)


f_jit = jax.jit(f)
"""
    assert all(v.rule not in ("R0", "R1") for v in lint_source(src))


def test_submit_through_functools_partial_resolves():
    """Call-graph resolution through functools.partial: the partial's
    underlying bound method roots the thread, so its jax touch fires
    R8."""
    src = """\
import functools
import threading

import jax.numpy as jnp


class Engine:
    def loop(self):
        return jnp.sum(self.buf)

    def start(self):
        t = threading.Thread(target=functools.partial(self.loop))
        t.start()
"""
    assert "R8" in {v.rule for v in lint_source(src)}


def test_submit_bound_method_of_typed_local_resolves():
    """pool.submit(obj.method): the receiver's class is inferred from
    its local construction, the method resolved, and its device touch
    attributed to the pool-worker root."""
    src = """\
from concurrent.futures import ThreadPoolExecutor

import jax.numpy as jnp


class Stager:
    def stage(self, g):
        return jnp.asarray(g)


def go(g):
    s = Stager()
    pool = ThreadPoolExecutor(1)
    return pool.submit(s.stage, g)
"""
    assert "R8" in {v.rule for v in lint_source(src)}


_PARAM_POOL_SNIPPET = """\
from concurrent.futures import ThreadPoolExecutor

import jax.numpy as jnp

from microrank_tpu.utils.guards import authorize_device_thread


class Lane:
    def start(self):
        pool = ThreadPoolExecutor(1, "s"{init})
        self.loop(pool)

    def loop(self, pool):
        return pool.submit(self.stage)

    def stage(self):
        return jnp.zeros(4)
"""


def test_executor_authorization_resolves_through_parameters():
    """The table-lane shape: the executor is constructed in one method
    and submitted to in another that receives it as a parameter — the
    authorization verdict must follow the value through the call."""
    authorized = _PARAM_POOL_SNIPPET.format(
        init=", initializer=authorize_device_thread"
    )
    assert "R8" not in {v.rule for v in lint_source(authorized)}
    unauthorized = _PARAM_POOL_SNIPPET.format(init="")
    assert "R8" in {v.rule for v in lint_source(unauthorized)}


# ---------------------------------------------------- mrrace (R10-R12)


def test_lock_model_identifies_attr_and_module_locks():
    from microrank_tpu.analysis.core import Project, _parse_text
    from pathlib import Path

    src = """\
import threading

_mod_lock = threading.Lock()


class S:
    def __init__(self):
        self._lock = threading.RLock()
"""
    project = Project([_parse_text(src, Path("<s>"), "<s>")])
    locks = project.locks
    assert ("S", "_lock") in locks.attr_locks
    assert locks.attr_locks[("S", "_lock")].reentrant
    assert any(
        name == "_mod_lock" for (_, name) in locks.module_locks
    )


def test_r10_locked_helper_inherits_caller_lockset():
    """The `_locked`-suffix helper pattern: every resolved caller holds
    the lock, so the helper's accesses inherit it and do NOT fire."""
    src = """\
import threading


class Coord:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def tick(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self.count = self.count + 1

    def start(self):
        t = threading.Thread(target=self.tick)
        t.start()

    def read(self):
        with self._lock:
            return self.count
"""
    assert "R10" not in {v.rule for v in lint_source(src)}


def test_r11_acquire_release_pairs_tracked():
    """Explicit acquire()/release() regions feed the order graph like
    `with` blocks do."""
    src = """\
import threading


class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        self._a.acquire()
        with self._b:
            pass
        self._a.release()

    def two(self):
        with self._b:
            self._a.acquire()
            self._a.release()
"""
    assert "R11" in {v.rule for v in lint_source(src)}


def test_r12_nested_callback_does_not_leak_lock(tmp_path):
    """A blocking call inside a nested def (deferred callback) is NOT
    attributed to the enclosing function's lexical lock region."""
    src = """\
import threading
import time


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = []

    def submit(self, fn):
        with self._lock:
            def run_later():
                time.sleep(1.0)
                return fn()

            self.jobs.append(run_later)
"""
    assert "R12" not in {v.rule for v in lint_source(src)}


def test_r12_fires_through_sleep_parameter_chain():
    """retry-style helpers: the sleep happens in a callee reached from
    a call made under the lock."""
    src = """\
import threading
import time


def backoff(delay):
    time.sleep(delay)


class Client:
    def __init__(self):
        self._lock = threading.Lock()
        self.sent = 0

    def send(self):
        with self._lock:
            backoff(0.1)
            self.sent += 1
"""
    fired = {v.rule for v in lint_source(src)}
    assert "R12" in fired


# ------------------------------------------------------------------- sarif


def test_sarif_rendering_round_trip():
    import json

    from microrank_tpu.analysis.sarif import to_sarif

    vs = lint_paths([str(DATA / "R8" / "bad_webhook_sink_fetch.py")])
    doc = to_sarif(vs)
    json.dumps(doc)  # serializable
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "mrlint"
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert rule_ids == sorted(["R0"] + list(RULES))
    (res,) = run["results"]
    assert res["ruleId"] == "R8" and res["level"] == "error"
    region = res["locations"][0]["physicalLocation"]["region"]
    assert region["startLine"] >= 1 and region["startColumn"] >= 1
    # ruleIndex points back into the driver catalog.
    assert rule_ids[res["ruleIndex"]] == "R8"


def test_cli_lint_sarif_flag(tmp_path, capsys):
    import json

    from microrank_tpu.cli.main import main

    out = tmp_path / "mrlint.sarif"
    bad = DATA / "R3" / "bad_tracer_branch.py"
    assert main(["lint", str(bad), "--sarif", str(out)]) == 1
    doc = json.loads(out.read_text())
    assert any(
        r["ruleId"] == "R3" for r in doc["runs"][0]["results"]
    )
    # A clean run still writes a (zero-result) SARIF for the upload step.
    good = DATA / "R3" / "good_cached_jit.py"
    assert main(["lint", str(good), "--sarif", str(out)]) == 0
    assert json.loads(out.read_text())["runs"][0]["results"] == []


def test_sarif_r0_reported_as_warning():
    from microrank_tpu.analysis import lint_source
    from microrank_tpu.analysis.sarif import to_sarif

    src = _BAD_SNIPPET.format(pragma="").replace(
        "return x * float(x)",
        "return x * float(x)  # mrlint: disable=R1",
    )
    doc = to_sarif(lint_source(src))
    levels = {
        r["ruleId"]: r["level"] for r in doc["runs"][0]["results"]
    }
    assert levels.get("R0") == "warning"


# ---------------------------------------------------------------- contracts


def test_contract_disabled_by_default():
    from microrank_tpu.spectrum.formulas import spectrum_scores

    a = np.ones(4, np.float32)
    bad = np.ones(4, np.float64)
    # No enforcement outside contract_checks: promotes silently.
    assert str(spectrum_scores(a, a, a, bad, "dstar2").dtype) == "float32"


def test_contract_dtype_and_dim_unification():
    from microrank_tpu.spectrum.formulas import spectrum_scores
    from microrank_tpu.utils.guards import ContractError, contract_checks

    a = np.ones(4, np.float32)
    with contract_checks(True):
        out = spectrum_scores(a, a, a, a, "dstar2")
        assert str(out.dtype) == "float32"
        with pytest.raises(ContractError, match="dtype float64"):
            spectrum_scores(a, a, a, np.ones(4, np.float64), "dstar2")
        with pytest.raises(ContractError, match="conflicts"):
            spectrum_scores(a, a, a, np.ones(5, np.float32), "dstar2")


def test_contract_on_rank_entry_point_trace_time():
    """The jitted rank path traces under an armed contract: a graph whose
    field dtype drifted from the structures.py layout is rejected before
    compilation."""
    import dataclasses

    import jax

    from microrank_tpu.config import MicroRankConfig
    from microrank_tpu.graph.build import build_window_graph
    from microrank_tpu.rank_backends.jax_tpu import rank_window_core
    from microrank_tpu.testing import SyntheticConfig, generate_case
    from microrank_tpu.utils.guards import ContractError, contract_checks

    cfg = MicroRankConfig()
    case = generate_case(
        SyntheticConfig(n_operations=8, n_kinds=4, n_traces=24, seed=0)
    )
    ids = sorted(set(case.abnormal["traceID"]))
    graph, names, _, _ = build_window_graph(
        case.abnormal, ids[::2], ids[1::2], aux="all"
    )
    with contract_checks(True):
        top_idx, top_scores, n_valid = rank_window_core(
            graph, cfg.pagerank, cfg.spectrum, None, "coo"
        )
        assert str(np.asarray(top_scores).dtype) == "float32"

        drifted = graph._replace(
            normal=graph.normal._replace(
                sr_val=np.asarray(graph.normal.sr_val, np.float64)
            )
        )
        with pytest.raises(ContractError, match="sr_val"):
            rank_window_core(drifted, cfg.pagerank, cfg.spectrum, None, "coo")


def test_contract_spec_parser_rejects_garbage():
    from microrank_tpu.analysis.contracts import parse_spec

    with pytest.raises(ValueError):
        parse_spec("float32[K")
    spec = parse_spec("int32[B,K]")
    assert spec.dims == ("b", "k") or spec.dims == ("B", "K")


def test_contract_decorator_rejects_unknown_param():
    from microrank_tpu.analysis.contracts import contract

    with pytest.raises(ValueError, match="unknown parameters"):

        @contract(nope="float32[K]")
        def f(x):
            return x
