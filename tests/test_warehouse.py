"""Trace warehouse (warehouse/): tiered columnar span store + time-travel.

Pins the subsystem's contracts: the frame codec is a value-exact round
trip (shared span/parent id dictionary, delta ints, datetime bases), the
host blob unpack is bit-exact against the device pack (so replaying a
stored blob through the SAME dispatch programs reproduces the live
scores bit-for-bit), segments restore the full detection context (op
vocab + SLO baseline snapshot), a corrupted manifest is rejected WHOLE
and rebuilt from a cold re-scan of the segment files, the journal
rotates with fsync-before-rename, and the two acceptance paths: an
in-process stream run whose warehouse replays to a "match" verdict and
retro-scores all 13 formulas, plus the crash seam — a stream subprocess
killed at ``warehouse_seal`` (between segment flush and checkpoint) and
resumed neither loses nor duplicates spans. All on CPU jax.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from conftest import partition_case
from microrank_tpu.config import (
    MicroRankConfig,
    StreamConfig,
    WarehouseConfig,
)
from microrank_tpu.graph.build import aux_for_kernel, build_window_graph
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.pipeline.results import WindowResult
from microrank_tpu.rank_backends.blob import pack_graph_blob
from microrank_tpu.stream import StreamEngine, SyntheticSource
from microrank_tpu.testing import SyntheticConfig
from microrank_tpu.warehouse import (
    MANIFEST_NAME,
    TraceWarehouse,
    WarehouseError,
    decode_frame,
    encode_frame,
    load_manifest,
    load_segment,
    load_warehouse_frame,
    parse_time_range,
    replay_range,
    run_retro,
    unpack_graph_blob_host,
    write_segment,
)
from microrank_tpu.warehouse.segment import encode_window


@pytest.fixture(autouse=True)
def _fresh_registry():
    old = get_registry()
    set_registry(MetricsRegistry())
    yield
    set_registry(old)


# ------------------------------------------------------------ frame codec


def _span_frame(n=40, seed=3):
    rng = np.random.default_rng(seed)
    ids = [f"s{i:04d}" for i in range(n)]
    parents = [None if i % 7 == 0 else ids[rng.integers(0, n)]
               for i in range(n)]
    t0 = pd.Timestamp("2025-03-01 00:00:00")
    return pd.DataFrame({
        "traceID": [f"t{i // 4}" for i in range(n)],
        "spanID": ids,
        "ParentSpanId": parents,
        "operationName": [f"op{i % 5}" for i in range(n)],
        "serviceName": [f"svc{i % 3}" for i in range(n)],
        "startTime": [t0 + pd.Timedelta(milliseconds=int(x))
                      for x in rng.integers(0, 60_000, n)],
        "duration_ms": rng.random(n).astype(np.float64) * 100,
        "status_code": rng.integers(0, 3, n).astype(np.int64),
        "is_error": (rng.random(n) < 0.1),
    })


def test_frame_codec_round_trip_exact():
    df = _span_frame()
    arrays, meta = encode_frame(df)
    # spanID and ParentSpanId share ONE dictionary (parents reference
    # span ids), and delta-encoded columns store small values.
    assert "iddict" in arrays and "col_spanID" in arrays
    assert "dict_spanID" not in arrays and "dict_ParentSpanId" not in arrays
    assert arrays["col_status_code"].min() == 0
    out = decode_frame(arrays, meta)
    assert list(out.columns) == list(df.columns)
    for col in df.columns:
        if df[col].dtype == object:
            a = df[col].where(df[col].notna(), None).tolist()
            b = out[col].where(out[col].notna(), None).tolist()
            assert a == b, col
        else:
            assert out[col].dtype == df[col].dtype, col
            pd.testing.assert_series_equal(
                out[col], df[col], check_names=False
            )


def test_frame_codec_empty_and_all_null_parent():
    df = _span_frame(6)
    df["ParentSpanId"] = None
    out = decode_frame(*encode_frame(df))
    assert out["ParentSpanId"].isna().all()
    empty = df.iloc[0:0]
    out2 = decode_frame(*encode_frame(empty))
    assert len(out2) == 0 and list(out2.columns) == list(df.columns)


# ----------------------------------------------------- blob + rank parity


def _graph_for(case, kernel="coo"):
    nrm, abn = partition_case(case)
    graph, op_names, _, _ = build_window_graph(
        case.abnormal, nrm, abn, aux=aux_for_kernel(kernel)
    )
    return graph, op_names


def test_host_blob_unpack_bit_exact(small_case):
    graph, _ = _graph_for(small_case)
    blob, layout = pack_graph_blob(graph)
    out = unpack_graph_blob_host(np.asarray(blob), layout)
    for part in ("normal", "abnormal"):
        src, dst = getattr(graph, part), getattr(out, part)
        for f, a, b in zip(src._fields, src, dst):
            a, b = np.asarray(a), np.asarray(b)
            assert a.shape == b.shape and a.dtype == b.dtype, f
            np.testing.assert_array_equal(
                np.atleast_1d(a).view(np.uint8),
                np.atleast_1d(b).view(np.uint8),
                err_msg=f"{part}.{f}",
            )


def test_segment_blob_round_trip_identical_scores(small_case, tmp_path):
    """The stored blob ranks bit-identically to the live graph through
    the same dispatch lane — the invariant `cli replay --at` gates on."""
    import jax

    from microrank_tpu.rank_backends.jax_tpu import rank_window_device

    cfg = MicroRankConfig()
    graph, op_names = _graph_for(small_case)
    blob, layout = pack_graph_blob(graph)
    rec = {
        "meta": {
            "start": "2025-03-01 00:00:00", "end": "2025-03-01 00:01:00",
            "start_us": 0, "end_us": 60_000_000,
            "outcome": "ranked", "spans": 0,
        },
        "graph_pack": (np.asarray(blob), layout, list(op_names)),
    }
    path = tmp_path / "seg-0-60000000.npz"
    write_segment(path, [encode_window(rec)])
    (w,) = load_segment(path)
    assert w.op_names == list(op_names) and w.kernel is None
    ref = jax.device_get(rank_window_device(
        jax.device_put(graph), cfg.pagerank, cfg.spectrum, None, "coo"
    ))
    got = jax.device_get(rank_window_device(
        jax.device_put(w.graph()), cfg.pagerank, cfg.spectrum, None, "coo"
    ))
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_segment_snapshot_restore_bit_faithful(tmp_path):
    vocab = [f"op{i}" for i in range(9)]
    mean = np.random.default_rng(0).random(9).astype(np.float32) * 50
    std = np.random.default_rng(1).random(9).astype(np.float32)

    class _Slo:
        mean_ms, std_ms = mean, std

    rec = {
        "meta": {"start": "a", "end": "b", "start_us": 0, "end_us": 1,
                 "outcome": "clean", "spans": 0},
        "snapshot": (vocab, _Slo),
    }
    path = tmp_path / "seg-0-1.npz"
    write_segment(path, [encode_window(rec)])
    (w,) = load_segment(path)
    assert w.vocab_names == vocab
    slo = w.slo_baseline()
    np.testing.assert_array_equal(
        slo.mean_ms.view(np.uint8), mean.view(np.uint8)
    )
    np.testing.assert_array_equal(
        slo.std_ms.view(np.uint8), std.view(np.uint8)
    )
    assert w.frame() is None and w.graph() is None


# ------------------------------------------------- manifest + store tiers


def _observe_n(store, n, spans_each=12, t0=pd.Timestamp("2025-03-01")):
    for i in range(n):
        start = t0 + pd.Timedelta(minutes=i)
        end = start + pd.Timedelta(minutes=1)
        res = WindowResult(start=str(start), end=str(end), anomaly=False)
        store.observe(res, "clean", frame=_span_frame(spans_each, seed=i))


def test_store_flush_compact_retention(tmp_path):
    cfg = WarehouseConfig(
        enabled=True, compact_after=3, retention_segments=2
    )
    store = TraceWarehouse(tmp_path, cfg)
    assert store.dir == tmp_path / "warehouse"
    _observe_n(store, 7)
    assert store.flush() == 7
    s = store.summary()
    # 7 warm -> two cold batches of 3, 1 warm leftover; retention keeps
    # the newest 2 segments by dropping the OLDEST cold segment.
    tiers = s["by_tier"]
    assert tiers.get("warm", 0) == 1 and tiers.get("cold", 0) == 1
    assert s["windows"] == 4 and s["spans"] == 4 * 12
    # Only manifest-listed files remain on disk.
    files = {f.name for f in store.dir.glob("*.npz")}
    assert files == {r["file"] for r in store._segments}
    # Re-open reads the same state back from the manifest.
    again = TraceWarehouse(tmp_path, cfg)
    assert again.summary() == s
    # query() honors bounds.
    t0 = pd.Timestamp("2025-03-01").value // 1000
    one = again.query(t0 + 4 * 60_000_000 + 1, t0 + 4 * 60_000_000 + 2)
    assert len(one) == 1 and one[0].frame() is not None


def test_manifest_corruption_rejected_whole_then_rescan(tmp_path):
    cfg = WarehouseConfig(enabled=True)
    store = TraceWarehouse(tmp_path, cfg)
    _observe_n(store, 2)
    store.flush()
    whdir = store.dir
    man = whdir / MANIFEST_NAME
    # Bit rot inside the payload: the whole manifest is rejected, not
    # partially trusted.
    doc = json.loads(man.read_text())
    doc["payload"]["counters"]["spans"] += 1
    man.write_text(json.dumps(doc))
    with pytest.raises(WarehouseError, match="checksum"):
        load_manifest(whdir)
    # Re-opening recovers via cold re-scan of the segment files and
    # re-seals a provably-intact manifest.
    recovered = TraceWarehouse(tmp_path, cfg)
    assert recovered.summary()["windows"] == 2
    assert recovered.summary()["spans"] == 24
    assert load_manifest(whdir)["counters"]["windows"] == 2
    # Torn JSON is equally fatal-then-recoverable.
    man.write_text('{"version": 1, "payload": {"seg')
    with pytest.raises(WarehouseError):
        load_manifest(whdir)
    assert TraceWarehouse(tmp_path, cfg).summary()["windows"] == 2


def test_reseal_same_window_is_idempotent(tmp_path):
    """The crash-consistency primitive: re-observing + re-flushing the
    SAME window replaces its segment row instead of double-counting."""
    cfg = WarehouseConfig(enabled=True)
    store = TraceWarehouse(tmp_path, cfg)
    _observe_n(store, 1)
    store.flush()
    _observe_n(store, 1)   # same bounds, same filename
    store.flush()
    s = store.summary()
    assert s["windows"] == 1 and s["spans"] == 12 and s["segments"] == 1


def test_parse_time_range():
    assert parse_time_range("all") == (None, None)
    assert parse_time_range("") == (None, None)
    assert parse_time_range("12..34") == (12, 34)
    assert parse_time_range("..34") == (None, 34)
    t = parse_time_range("2025-03-01 00:00:00..")
    assert t == (pd.Timestamp("2025-03-01").value // 1000, None)
    assert parse_time_range("7") == (7, 7)


# --------------------------------------------------- journal rotation


def test_journal_size_rotation_and_multipart_read(tmp_path):
    from microrank_tpu.obs.journal import (
        RunJournal,
        journal_parts,
        read_journal,
    )

    path = tmp_path / "journal.jsonl"
    j = RunJournal(path, max_bytes=600)
    for i in range(40):
        j.emit("tick", i=i, pad="x" * 40)
    parts = journal_parts(path)
    assert parts, "no rotation happened; shrink max_bytes"
    # Rotated parts + live file carry every event exactly once, in order.
    events = [e for e in read_journal(path) if e["event"] == "tick"]
    assert [e["i"] for e in events] == list(range(40))
    assert all(p.stat().st_size <= 600 + 200 for p in parts)


# ------------------------------------------- e2e: stream -> replay/retro


@pytest.fixture(scope="module")
def wh_run(tmp_path_factory):
    """One in-process stream run with the warehouse armed: 8 windows,
    2 faulted, cold compaction after 4 warm segments."""
    out_dir = tmp_path_factory.mktemp("wh_run")
    old = get_registry()
    set_registry(MetricsRegistry())
    try:
        cfg = MicroRankConfig(
            stream=StreamConfig(allowed_lateness_seconds=5.0),
            warehouse=WarehouseConfig(enabled=True, compact_after=4),
        )
        src = SyntheticSource(
            n_windows=8, faulted=[4, 5],
            synth_config=SyntheticConfig(
                n_operations=12, n_traces=50, seed=11
            ),
        )
        eng = StreamEngine(cfg, src, out_dir=out_dir)
        summary = eng.run()
    finally:
        set_registry(old)
    return {"out_dir": out_dir, "summary": summary, "source": src,
            "config": cfg}


def test_stream_seals_tiered_segments(wh_run):
    whdir = wh_run["out_dir"] / "warehouse"
    payload = load_manifest(whdir)
    assert payload["counters"]["windows"] == 8
    tiers = {r["tier"] for r in payload["segments"]}
    assert "cold" in tiers, "compaction never ran"
    # Ground truth from the synthetic source rides in the manifest.
    assert payload["truth"]
    # Detection context: every post-warmup window carries the snapshot.
    store = TraceWarehouse(whdir, wh_run["config"].warehouse)
    ranked = [w for w in store.query() if w.outcome == "ranked"]
    assert ranked and all(
        w.vocab_names and w.slo_baseline() is not None for w in ranked
    )
    assert all(w.graph() is not None for w in ranked)


def test_replay_range_matches_live_verdicts(wh_run):
    report = replay_range(wh_run["out_dir"], None, None,
                          config=wh_run["config"])
    assert report["verdict"] == "match", report["mismatched"]
    assert report["ranked"] == report["matched"] == 2
    assert report["skipped_no_blob"] == 0
    # A bounded range narrows to its window(s).
    store = TraceWarehouse(
        wh_run["out_dir"] / "warehouse", wh_run["config"].warehouse
    )
    w0 = [w for w in store.query() if w.outcome == "ranked"][0]
    narrow = replay_range(
        wh_run["out_dir"], w0.start_us, w0.start_us + 1,
        config=wh_run["config"],
    )
    assert narrow["ranked"] == narrow["matched"] == 1
    assert narrow["verdict"] == "match"


def test_replay_source_warehouse_segment_mode(wh_run):
    from microrank_tpu.stream.sources import ReplaySource

    df = load_warehouse_frame(wh_run["out_dir"])
    payload = load_manifest(wh_run["out_dir"] / "warehouse")
    assert len(df) == payload["counters"]["spans"]
    src = ReplaySource(wh_run["out_dir"], chunk_spans=100_000)
    assert sum(len(c) for c in src) == len(df)


def test_retro_scoring_feeds_policy_engine(wh_run, tmp_path, monkeypatch):
    monkeypatch.setenv("MICRORANK_POLICY_DIR", str(tmp_path))
    result = run_retro(
        wh_run["out_dir"], config=wh_run["config"], seed=0,
        persist_policy=True,
    )
    rec = result["record"]
    assert result["outcome_source"] == "manifest"
    assert rec["formulas"] and len(rec["formulas"]) == 13
    for row in rec["formulas"].values():
        assert 0.0 <= row["map"] <= 1.0 and row["windows"] == 2
    assert rec["profile"] and rec["family"] == "warehouse"
    assert result["policy"]["profiles"]
    assert result["policy_path"] and Path(result["policy_path"]).exists()
    assert (wh_run["out_dir"] / "warehouse" / "retro_matrix.json").exists()


# --------------------------------------- crash consistency at the seal


def test_warehouse_seal_crash_consistency(tmp_path):
    """Kill the process AT the ``warehouse_seal`` seam — after segment
    files hit disk, before manifest + checkpoint — then ``--resume``:
    the warehouse ends byte-for-byte equivalent to a never-crashed run
    (no lost windows, no duplicated spans)."""
    src = SyntheticSource(
        n_windows=6, faulted=[3],
        synth_config=SyntheticConfig(
            n_operations=12, n_traces=50, seed=11
        ),
    )
    input_csv = tmp_path / "timeline.csv"
    normal_csv = tmp_path / "normal.csv"
    src.timeline.timeline.to_csv(input_csv, index=False)
    src.normal.to_csv(normal_csv, index=False)
    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps({
        "seed": 0,
        "faults": [{"seam": "warehouse_seal", "kind": "kill", "count": 1}],
    }))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(Path(__file__).parent.parent),
    }

    def _run(out, extra):
        return subprocess.run(
            [
                sys.executable, "-m", "microrank_tpu.cli", "stream",
                "--source", "replay", "--input", str(input_csv),
                "--lateness-seconds", "5", "--warehouse",
                "-o", str(out), *extra,
            ],
            env=env, capture_output=True, text=True, timeout=600,
        )

    ref = _run(tmp_path / "ref", ["--normal", str(normal_csv)])
    assert ref.returncode == 0, ref.stdout + ref.stderr
    ref_manifest = load_manifest(tmp_path / "ref" / "warehouse")

    out = tmp_path / "out"
    crashed = _run(out, ["--normal", str(normal_csv),
                         "--chaos", str(plan)])
    assert crashed.returncode == 137, (
        f"expected the injected kill (137), got {crashed.returncode}:\n"
        + crashed.stdout + crashed.stderr
    )
    # Torn state: segment file(s) exist but the manifest does not list
    # them yet (or does not exist at all).
    whdir = out / "warehouse"
    orphans = list(whdir.glob("seg-*.npz"))
    assert orphans, "kill fired before any segment flush"
    try:
        sealed = load_manifest(whdir) or {"segments": []}
    except WarehouseError:
        sealed = {"segments": []}
    assert len(sealed["segments"]) < len(orphans) or not sealed["segments"]

    # Resume WITHOUT the plan (fault counts are per-process; the crash
    # already happened) — the re-seal must absorb the orphan segments.
    resumed = _run(out, ["--resume"])
    assert resumed.returncode == 0, resumed.stdout + resumed.stderr
    got = load_manifest(whdir)
    assert got["counters"]["windows"] == ref_manifest["counters"]["windows"]
    assert got["counters"]["spans"] == ref_manifest["counters"]["spans"]
    ref_files = sorted(
        (r["file"], r["spans"]) for r in ref_manifest["segments"]
    )
    got_files = sorted((r["file"], r["spans"]) for r in got["segments"])
    assert got_files == ref_files
    # And the recovered history replays clean.
    report = replay_range(out, None, None)
    assert report["verdict"] == "match", report["mismatched"]
