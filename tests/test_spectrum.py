"""Spectrum formulas: vectorized jnp vs the oracle's scalar forms."""

import jax.numpy as jnp
import numpy as np
import pytest

from microrank_tpu.rank_backends.numpy_ref import spectrum_score
from microrank_tpu.spectrum import METHODS, spectrum_scores


@pytest.mark.parametrize("method", METHODS)
def test_vectorized_matches_scalar(method):
    rng = np.random.default_rng(0)
    n = 64
    ef = rng.uniform(1e-7, 10, n)
    nf = rng.uniform(1e-7, 10, n)
    ep = rng.uniform(1e-7, 10, n)
    np_ = rng.uniform(1e-7, 10, n)
    got = np.asarray(
        spectrum_scores(
            jnp.asarray(ef, jnp.float64) if False else jnp.asarray(ef, jnp.float32),
            jnp.asarray(nf, jnp.float32),
            jnp.asarray(ep, jnp.float32),
            jnp.asarray(np_, jnp.float32),
            method,
        )
    )
    exp = np.array(
        [
            spectrum_score(
                {"ef": ef[i], "nf": nf[i], "ep": ep[i], "np": np_[i]}, method
            )
            for i in range(n)
        ]
    )
    np.testing.assert_allclose(got, exp, rtol=2e-5)


def test_unknown_method_raises():
    with pytest.raises(ValueError):
        spectrum_scores(
            jnp.ones(1), jnp.ones(1), jnp.ones(1), jnp.ones(1), "nope"
        )
