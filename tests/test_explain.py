"""Rank provenance (explain/) — oracle parity, bundles, and the API.

The explain acceptance gate: device-side attribution tensors (the
per-suspect ef/nf/ep/np counter decomposition, the per-formula term
values across all 13 spectrum formulas, the normal/abnormal PPR mass
split, and the top contributing coverage columns) must agree tie-aware
with the float64 numpy oracle on EVERY kernel family (coo/csr/packed/
pcsr), on collapsed AND uncollapsed builds, and on the sharded path.
Plus: the hot path is untouched when explain is off, bundles
materialize on incident open (next to the flight dump, cross-linked in
its manifest), `GET /explainz` serves the store, `cli explain` renders
run artifacts, serve honors `explain:true` + W3C `traceparent` +
`Server-Timing`, and the incident webhook is timeout-bounded with the
enriched payload.
"""

import json
import socket
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import partition_case
from microrank_tpu.config import (
    ExplainConfig,
    MicroRankConfig,
    ServeConfig,
    StreamConfig,
)
from microrank_tpu.explain import build_bundle, get_explain_store
from microrank_tpu.explain.bundle import (
    BUNDLE_JSON,
    BUNDLE_TXT,
    ExplainBundle,
    ExplainContext,
)
from microrank_tpu.explain.oracle import explain_window_oracle
from microrank_tpu.explain.store import ExplainStore
from microrank_tpu.graph.build import PCSR_PART_TRACES, build_window_graph
from microrank_tpu.obs import (
    MetricsRegistry,
    get_registry,
    read_journal,
    set_registry,
)
from microrank_tpu.parallel import (
    make_mesh,
    rank_windows_explained_sharded,
    stack_window_graphs,
)
from microrank_tpu.rank_backends.blob import stage_rank_window
from microrank_tpu.rank_backends.jax_tpu import device_subset
from microrank_tpu.serve.protocol import (
    parse_rank_request,
    parse_traceparent,
    server_timing_header,
)
from microrank_tpu.stream import (
    IncidentTracker,
    StreamEngine,
    SyntheticSource,
    WebhookIncidentSink,
)
from microrank_tpu.testing import SyntheticConfig, generate_case
from microrank_tpu.utils.ranking_compare import tie_aware_topk_agreement

CFG = MicroRankConfig()
EXPLAIN = ExplainConfig(enabled=True, top_traces=5)
KERNELS = ("coo", "csr", "packed", "pcsr")


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture(scope="module")
def kind_case():
    """Strong kind structure — collapse genuinely shrinks the axis, so
    the collapsed parametrization exercises the retention map."""
    return generate_case(
        SyntheticConfig(n_operations=60, n_kinds=6, n_traces=400, seed=3)
    )


@pytest.fixture(scope="module")
def builds(kind_case):
    """(graph, names, ectx) per collapse mode + the uncollapsed oracle
    inputs (graph, names, trace-id lists) the f64 twin recomputes on."""
    nrm, abn = partition_case(kind_case)
    out = {}
    for collapse in ("off", "on"):
        g, names, ids_n, ids_a, (mn, ma) = build_window_graph(
            kind_case.abnormal, nrm, abn, aux="all", collapse=collapse,
            retain_columns=True,
        )
        out[collapse] = (
            g, names, ExplainContext.from_build(g, ids_n, ids_a, mn, ma)
        )
    g_un, names_u, idsn, idsa = build_window_graph(
        kind_case.abnormal, nrm, abn, aux="all", collapse="off"
    )
    out["oracle_inputs"] = (g_un, names_u, idsn, idsa)
    return out


@pytest.fixture(scope="module")
def oracles(builds):
    g_un, names_u, idsn, idsa = builds["oracle_inputs"]
    return {
        collapse: explain_window_oracle(
            g_un, names_u, idsn, idsa, CFG.pagerank, CFG.spectrum,
            top_traces=None, aggregate_kinds=(collapse == "on"),
        )
        for collapse in ("off", "on")
    }


def _device_bundle(graph, names, ectx, kernel, blob=False, ex=EXPLAIN):
    outs = jax.device_get(
        stage_rank_window(
            device_subset(graph, kernel), CFG.pagerank, CFG.spectrum,
            kernel, blob, explain=ex,
        )
    )
    assert len(outs) == 10  # the 5 traced-rank outputs + 5 attribution
    return build_bundle(
        outs, names, ectx, method=CFG.spectrum.method, kernel=kernel
    )


def _assert_bundle_matches_oracle(bundle, oracle, rtol=2e-5):
    """The acceptance comparison: tie-aware suspect list, then
    per-suspect counters/terms/mass/contributions against the f64
    oracle (matched by op name, so legally permuted exact ties still
    compare the right decompositions)."""
    dev, orc = bundle.suspects, oracle["suspects"]
    assert len(dev) == len(orc)
    agree, reason = tie_aware_topk_agreement(
        [s["op"] for s in dev], [s["score"] for s in dev],
        [s["op"] for s in orc], [s["score"] for s in orc],
        k=len(dev), rtol=1e-4, exempt_last=True,
    )
    assert agree, reason
    by_op = {s["op"]: s for s in orc}
    missing = [s["op"] for s in dev if s["op"] not in by_op]
    assert len(missing) <= 1, missing  # only a cut-straddling near-tie
    for s in dev:
        o = by_op.get(s["op"])
        if o is None:
            continue
        for c in ("ef", "nf", "ep", "np"):
            assert np.isclose(
                s["counters"][c], o["counters"][c], rtol=rtol
            ), (s["op"], c, s["counters"][c], o["counters"][c])
        for side in ("normal_weight", "abnormal_weight"):
            assert np.isclose(
                s["mass"][side], o["mass"][side], rtol=rtol, atol=1e-12
            ), (s["op"], side)
        for m, val in s["terms"].items():
            assert np.isclose(
                val, o["terms"][m], rtol=5e-4, atol=1e-9
            ), (s["op"], m, val, o["terms"][m])
        for p in ("normal", "abnormal"):
            omap = dict(o["top_traces"][p])
            entries = s["top_traces"][p]
            for e in entries:
                assert "trace" in e, (s["op"], p, e)  # ectx joined
                assert e["trace"] in omap, (s["op"], p, e)
                assert np.isclose(
                    e["contribution"], omap[e["trace"]], rtol=5e-4
                ), (s["op"], p, e["trace"])
            if entries:
                # Tie-aware top-J set: every oracle contributor that
                # beats the device cut (beyond tie tolerance) is kept.
                cut = min(e["contribution"] for e in entries)
                kept = {e["trace"] for e in entries}
                if len(entries) == len(
                    [v for v in omap.values() if v > 0]
                ):
                    assert kept == {
                        t for t, v in omap.items() if v > 0
                    }, (s["op"], p)
                else:
                    beat = {
                        t for t, v in omap.items()
                        if v > cut * (1 + 1e-3)
                    }
                    assert beat <= kept, (s["op"], p, beat - kept)


# ----------------------------------------------------- oracle parity


@pytest.mark.parametrize("collapse", ["off", "on"])
@pytest.mark.parametrize("kernel", KERNELS)
def test_explain_parity_oracle(builds, oracles, kernel, collapse):
    graph, names, ectx = builds[collapse]
    bundle = _device_bundle(graph, names, ectx, kernel)
    _assert_bundle_matches_oracle(bundle, oracles[collapse])


def test_explain_parity_blob_staging(builds, oracles):
    """The blob-staged explained twin (unpack inside the program) pins
    the same oracle — the codec carries every field the epilogue needs."""
    graph, names, ectx = builds["on"]
    bundle = _device_bundle(graph, names, ectx, "coo", blob=True)
    _assert_bundle_matches_oracle(bundle, oracles["on"])


def test_explain_top_suspects_truncates(builds):
    graph, names, ectx = builds["off"]
    ex = ExplainConfig(enabled=True, top_traces=3, top_suspects=2)
    bundle = _device_bundle(graph, names, ectx, "coo", ex=ex)
    assert len(bundle.suspects) == 2
    for s in bundle.suspects:
        for p in ("normal", "abnormal"):
            assert len(s["top_traces"][p]) <= 3


def test_explain_off_dispatches_plain_program(builds):
    """The hot-path guarantee: explain=None or enabled=False dispatches
    the UNCHANGED traced program (5-tuple), not the explained twin."""
    graph, names, _ = builds["off"]
    g = device_subset(graph, "coo")
    plain = stage_rank_window(
        g, CFG.pagerank, CFG.spectrum, "coo", False, conv_trace=True
    )
    assert len(plain) == 5
    off = stage_rank_window(
        g, CFG.pagerank, CFG.spectrum, "coo", False, conv_trace=True,
        explain=ExplainConfig(enabled=False),
    )
    assert len(off) == 5
    # And the first five explained outputs ARE the traced outputs.
    exp = jax.device_get(
        stage_rank_window(
            g, CFG.pagerank, CFG.spectrum, "coo", False, explain=EXPLAIN
        )
    )
    np.testing.assert_array_equal(
        np.asarray(plain[0]), np.asarray(exp[0])
    )
    np.testing.assert_allclose(
        np.asarray(plain[1]), np.asarray(exp[1]), rtol=1e-6
    )


# ----------------------------------------------------- sharded path


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)
@pytest.mark.parametrize(
    "kernel,trace_multiple",
    [("coo", 1), ("csr", 1), ("packed", 32), ("pcsr", PCSR_PART_TRACES * 4)],
)
def test_explained_sharded_matches_oracle(kernel, trace_multiple):
    """The sharded epilogue (psum'd scatter partials / all-gathered
    bitmap blocks) replicates the same attributions: every window of a
    (2, 4)-mesh batch pins the f64 oracle like the single-device twin."""
    cfg = MicroRankConfig()
    windows = []
    for seed in (1, 2, 3, 4):
        case = generate_case(
            SyntheticConfig(n_operations=20, n_traces=100, seed=seed)
        )
        nrm, abn = partition_case(case)
        g, names, idsn, idsa, cmap = build_window_graph(
            case.abnormal, nrm, abn, aux="all", retain_columns=True
        )
        ectx = ExplainContext.from_build(g, idsn, idsa, *cmap)
        oracle = explain_window_oracle(
            g, names, idsn, idsa, cfg.pagerank, cfg.spectrum,
            top_traces=None,
        )
        windows.append((g, names, ectx, oracle))
    mesh = make_mesh((2, 4))
    stacked = stack_window_graphs(
        [g for g, _, _, _ in windows],
        shard_multiple=4, trace_multiple=trace_multiple,
    )
    outs = jax.device_get(
        rank_windows_explained_sharded(
            jax.tree.map(jnp.asarray, stacked), cfg.pagerank,
            cfg.spectrum, EXPLAIN, mesh, kernel,
        )
    )
    assert len(outs) == 10
    for b, (g, names, ectx, oracle) in enumerate(windows):
        bundle = build_bundle(
            tuple(o[b] for o in outs), names, ectx,
            method=cfg.spectrum.method, kernel=kernel,
        )
        # Cross-shard psum reassociation wobbles the f32 partials a
        # touch more than the single-device summation trees.
        _assert_bundle_matches_oracle(bundle, oracle, rtol=5e-4)


# ------------------------------------------------- bundle + store + API


def test_bundle_roundtrip_table_and_journal_record(builds, tmp_path):
    graph, names, ectx = builds["off"]
    bundle = _device_bundle(graph, names, ectx, "coo")
    bundle.data["window"] = {"start": "w0", "end": "w1"}
    path = bundle.write(tmp_path / "b")
    assert path.name == BUNDLE_JSON
    assert (tmp_path / "b" / BUNDLE_TXT).exists()
    loaded = ExplainBundle.load(path)
    assert loaded.data == bundle.data
    assert loaded.top1() == bundle.suspects[0]["op"]
    table = loaded.to_table()
    assert bundle.suspects[0]["op"] in table
    assert "counters ef=" in table and "formulas" in table
    rec = loaded.journal_record()
    assert rec["top1"] == bundle.suspects[0]["op"]
    assert rec["ef_top1"] == pytest.approx(
        bundle.suspects[0]["counters"]["ef"]
    )
    assert rec["start"] == "w0" and rec["suspects"] == len(
        bundle.suspects
    )


def test_explain_store_ring_evicts_oldest():
    store = ExplainStore(capacity=2)
    for i in range(3):
        store.publish(f"w{i}", {"n": i})
    assert store.windows() == ["w1", "w2"]
    assert store.get("w0") is None
    assert store.get("w1") == {"n": 1}
    assert store.latest() == {"n": 2}
    store.configure(capacity=1)
    assert store.windows() == ["w2"]
    # Republish moves to the back instead of duplicating.
    store.publish("w2", {"n": 9})
    assert len(store) == 1 and store.latest() == {"n": 9}


def test_explainz_endpoint_serves_store(registry):
    from microrank_tpu.obs.server import start_metrics_server

    get_explain_store().publish("2020-01-01 00:00:00", {"schema": 1})
    server = start_metrics_server(0, registry)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/explainz", timeout=30) as r:
            listing = json.loads(r.read())
        assert "2020-01-01 00:00:00" in listing["windows"]
        assert listing["latest"]["schema"] == 1
        with urllib.request.urlopen(
            f"{base}/explainz?window=2020-01-01%2000:00:00", timeout=30
        ) as r:
            assert json.loads(r.read()) == {"schema": 1}
        try:
            urllib.request.urlopen(f"{base}/explainz?window=nope", timeout=30)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.close()


# --------------------------------------------- serve protocol satellites


def test_parse_traceparent():
    tid, sid = "0af7651916cd43dd8448eb211c80319c", "b7ad6b7169203331"
    assert parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid)
    assert parse_traceparent(f"  00-{tid.upper()}-{sid}-01 ") == (tid, sid)
    assert parse_traceparent(None) is None
    assert parse_traceparent("") is None
    assert parse_traceparent("garbage") is None
    assert parse_traceparent(f"00-{tid}-{sid}") is None
    assert parse_traceparent(f"00-{'0' * 32}-{sid}-01") is None
    assert parse_traceparent(f"00-{tid}-{'0' * 16}-01") is None
    req = parse_rank_request(
        json.dumps({"spans": [{"a": 1}], "explain": True}).encode(),
        traceparent=f"00-{tid}-{sid}-01",
    )
    assert req.explain is True and req.traceparent == (tid, sid)


def test_server_timing_header_renders_stage_timings():
    hdr = server_timing_header(
        {"parse_ms": 1.5, "detect_ms": 0.25, "total": 9, "rank_ms": 12.0}
    )
    assert hdr == "parse;dur=1.500, detect;dur=0.250, rank;dur=12.000"
    assert server_timing_header({}) is None


# ------------------------------------------------- webhook satellites


def test_webhook_timeout_bounds_hung_endpoint():
    """A wedged endpoint (accepts, never responds) costs at most the
    explicit timeout — the engine-thread stall bound."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    try:
        sink = WebhookIncidentSink(
            f"http://127.0.0.1:{srv.getsockname()[1]}/hook", timeout=0.5
        )
        t0 = time.monotonic()
        sink.emit({"event": "incident_open"})
        elapsed = time.monotonic() - t0
        assert sink.failures == 1
        assert elapsed < 5.0, elapsed
    finally:
        srv.close()


def test_incident_open_payload_enriched(registry):
    """The open event carries the tie-aware top-k suspects WITH scores
    and the on_open hook's extras (the explain-bundle path); a failing
    hook never blocks alerting."""
    events = []
    tracker = IncidentTracker(
        top_k=2, sinks=[type("S", (), {"emit": lambda self, e: events.append(e)})()]
    )
    ranking = [("op-a", 1.0), ("op-b", 0.5), ("op-c", 0.1)]
    inc = tracker.observe_ranked(
        "w0", ranking, on_open=lambda i: {"explain_bundle": "/p/b.json"}
    )
    assert inc is not None
    assert events[0]["event"] == "incident_open"
    assert events[0]["suspects"] == [["op-a", 1.0], ["op-b", 0.5]]
    assert events[0]["explain_bundle"] == "/p/b.json"
    # Hook failure containment: the incident still opens, sans extras.
    events.clear()
    tracker2 = IncidentTracker(
        top_k=2, sinks=[type("S", (), {"emit": lambda self, e: events.append(e)})()]
    )
    inc2 = tracker2.observe_ranked(
        "w0", ranking, on_open=lambda i: 1 / 0
    )
    assert inc2 is not None and events[0]["event"] == "incident_open"
    assert "explain_bundle" not in events[0]


# -------------------------------------------------- stream end-to-end


class _CaptureHook(BaseHTTPRequestHandler):
    bodies = None

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        type(self).bodies.append(json.loads(self.rfile.read(n)))
        self.send_response(200)
        self.end_headers()

    def log_message(self, *a):  # noqa: D102 - quiet test output
        pass


def test_stream_incident_opens_with_explain_bundle(registry, tmp_path):
    """Acceptance (stream): injected fault -> incident opens -> the
    bundle lands under out_dir/explain/, next to the flight dump with
    the manifest cross-link, mirrored into the journal (top-1/ef match
    the ranked window), published to the /explainz store, and the
    webhook open payload names suspects + the bundle path."""
    bodies = []
    _CaptureHook.bodies = bodies
    hook = HTTPServer(("127.0.0.1", 0), _CaptureHook)
    threading.Thread(target=hook.serve_forever, daemon=True).start()
    src = SyntheticSource(
        n_windows=8,
        faulted=[3],
        synth_config=SyntheticConfig(
            n_operations=24, n_traces=200, n_kinds=16, seed=5
        ),
        pace_seconds=0.01,
        sleep=lambda s: None,
    )
    cfg = MicroRankConfig(
        stream=StreamConfig(
            allowed_lateness_seconds=5.0,
            webhook_url=f"http://127.0.0.1:{hook.server_port}/hook",
            webhook_timeout_seconds=10.0,
        ),
        explain=ExplainConfig(enabled=True),
    )
    try:
        eng = StreamEngine(cfg, src, out_dir=tmp_path)
        s = eng.run()
    finally:
        hook.shutdown()
        hook.server_close()
    assert s.incidents_opened == 1
    # Bundle on disk under out_dir/explain/<window-stem>/.
    bundle_dirs = list((tmp_path / "explain").iterdir())
    assert len(bundle_dirs) == 1
    bundle = ExplainBundle.load(bundle_dirs[0] / BUNDLE_JSON)
    assert bundle.data["trigger"] == "incident"
    assert bundle.suspects and src.fault_pod_op in [
        sus["op"] for sus in bundle.suspects[:5]
    ]
    for sus in bundle.suspects:
        assert set(sus["counters"]) == {"ef", "nf", "ep", "np"}
        assert len(sus["terms"]) == 13
    # Journal mirror: explain event top-1/ef consistent with the ranked
    # window event (the CI smoke's cross-check).
    jev = read_journal(tmp_path / "journal.jsonl")
    exp = [e for e in jev if e["event"] == "explain"]
    assert len(exp) == 1
    ranked = [
        e for e in jev
        if e["event"] == "window" and e.get("outcome") == "ranked"
    ]
    assert exp[0]["top1"] == ranked[0]["top1"]
    assert exp[0]["ef_top1"] == pytest.approx(
        bundle.suspects[0]["counters"]["ef"]
    )
    assert exp[0]["bundle"] == str(bundle_dirs[0] / BUNDLE_JSON)
    # Next to the flight dump, cross-linked in its manifest.
    dumps = [
        d for d in (tmp_path / "flight").iterdir() if "incident" in d.name
    ]
    assert len(dumps) == 1
    assert (dumps[0] / BUNDLE_JSON).exists()
    manifest = json.loads((dumps[0] / "manifest.json").read_text())
    assert manifest["explain_bundle"] == BUNDLE_JSON
    # Store published (what /explainz serves).
    stored = get_explain_store().get(str(ranked[0]["start"]))
    assert stored is not None and stored["suspects"] == bundle.data[
        "suspects"
    ]
    # Webhook open payload: suspects with scores + the bundle path.
    opens = [b for b in bodies if b["event"] == "incident_open"]
    assert len(opens) == 1
    assert opens[0]["suspects"][0][0] == bundle.suspects[0]["op"]
    assert opens[0]["explain_bundle"] == str(
        bundle_dirs[0] / BUNDLE_JSON
    )
    assert (
        registry.get("microrank_explain_bundles_total").value(
            trigger="incident"
        )
        == 1
    )


def test_stream_explain_off_writes_nothing(registry, tmp_path):
    src = SyntheticSource(
        n_windows=6,
        faulted=[2],
        synth_config=SyntheticConfig(
            n_operations=24, n_traces=200, n_kinds=16, seed=5
        ),
        pace_seconds=0.01,
        sleep=lambda s: None,
    )
    cfg = MicroRankConfig(
        stream=StreamConfig(allowed_lateness_seconds=5.0)
    )
    eng = StreamEngine(cfg, src, out_dir=tmp_path)
    s = eng.run()
    assert s.incidents_opened == 1
    assert not (tmp_path / "explain").exists()
    assert not [
        e
        for e in read_journal(tmp_path / "journal.jsonl")
        if e["event"] == "explain"
    ]


# ----------------------------------------------------- cli explain


def test_cli_explain_renders_run_artifacts(registry, tmp_path, capsys):
    from microrank_tpu.cli.main import main

    src = SyntheticSource(
        n_windows=6,
        faulted=[2],
        synth_config=SyntheticConfig(
            n_operations=24, n_traces=200, n_kinds=16, seed=5
        ),
        pace_seconds=0.01,
        sleep=lambda s: None,
    )
    cfg = MicroRankConfig(
        stream=StreamConfig(allowed_lateness_seconds=5.0),
        explain=ExplainConfig(enabled=True),
    )
    eng = StreamEngine(cfg, src, out_dir=tmp_path)
    eng.run()
    bundle_dir = next((tmp_path / "explain").iterdir())
    top1 = ExplainBundle.load(bundle_dir / BUNDLE_JSON).top1()
    # Run output dir -> table rendering.
    assert main(["explain", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "Rank provenance" in out and top1 in out
    # Bundle dir and raw JSON formats; --json sidecar write.
    sidecar = tmp_path / "picked.json"
    assert (
        main(
            [
                "explain", str(bundle_dir), "--format", "json",
                "--json", str(sidecar),
            ]
        )
        == 0
    )
    data = json.loads(capsys.readouterr().out)
    assert data["suspects"][0]["op"] == top1
    assert json.loads(sidecar.read_text()) == data
    # Flight dump dir (the cross-linked copy) renders too.
    dump = next(
        d for d in (tmp_path / "flight").iterdir() if "incident" in d.name
    )
    assert main(["explain", str(dump)]) == 0
    assert top1 in capsys.readouterr().out
    # Window filter: hit and miss.
    start = data["window"]["start"]
    assert main(["explain", str(tmp_path), "--window", start]) == 0
    capsys.readouterr()
    assert main(["explain", str(tmp_path), "--window", "nope"]) == 2
    assert main(["explain", str(tmp_path / "missing")]) == 2


# -------------------------------------------------- serve end-to-end


def _post_rank(port, payload, headers=None, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/rank",
        data=json.dumps(payload).encode(),
        method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def test_serve_explain_traceparent_server_timing(registry, tmp_path):
    """POST /rank with explain:true returns the bundle inline; the
    traceparent header joins the request trace to the caller's; every
    200 carries Server-Timing stage durations. A request that did not
    ask pays nothing (no explain field, one dispatch)."""
    from microrank_tpu.obs.spans import get_tracer
    from microrank_tpu.serve import ServeHandle, ServeService

    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    cfg = MicroRankConfig(
        serve=ServeConfig(warmup=False, max_wait_ms=2000.0)
    )
    svc = ServeService(cfg, out_dir=tmp_path)
    svc.fit_baseline(case.normal)
    svc.start()
    handle = ServeHandle(svc)
    port = handle.start()
    df = case.abnormal.copy()
    df["startTime"] = df["startTime"].astype(str)
    df["endTime"] = df["endTime"].astype(str)
    spans = df.to_dict("records")
    trace_id = "0af7651916cd43dd8448eb211c80319c"
    parent = "b7ad6b7169203331"
    try:
        status, body, headers = _post_rank(
            port,
            {"spans": spans, "explain": True, "request_id": "r-exp"},
            headers={"traceparent": f"00-{trace_id}-{parent}-01"},
        )
        assert status == 200 and body["anomaly"] is True
        exp = body["explain"]
        assert exp["trigger"] == "request"
        assert exp["window"]["request_id"] == "r-exp"
        assert exp["suspects"][0]["op"] == body["ranking"][0][0]
        assert set(exp["suspects"][0]["counters"]) == {
            "ef", "nf", "ep", "np",
        }
        assert exp["suspects"][0]["top_traces"]["abnormal"]
        timing = headers.get("Server-Timing", "")
        for stage in ("parse", "detect", "rank"):
            assert f"{stage};dur=" in timing, timing
        # The explained request's spans joined the CALLER's trace.
        spans_ring = [
            s for s in get_tracer().snapshot()
            if s.trace_id == trace_id
        ]
        names = {s.name for s in spans_ring}
        assert "request" in names and "explain" in names
        parents = {
            s.parent_id for s in spans_ring if s.name == "request"
        }
        assert parents == {parent}
        assert (
            registry.get("microrank_explain_bundles_total").value(
                trigger="request"
            )
            == 1
        )
        # Store published under the window start for /explainz.
        assert get_explain_store().get(str(body["start"])) is not None
        # No explain asked -> no bundle, nothing extra dispatched.
        dispatches = svc.scheduler.batcher.dispatches
        status2, body2, headers2 = _post_rank(port, {"spans": spans})
        assert status2 == 200
        assert body2.get("explain") is None
        assert "Server-Timing" in headers2
        assert svc.scheduler.batcher.dispatches == dispatches + 1
    finally:
        handle.stop()
