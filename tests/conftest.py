"""Test env: force CPU with an 8-device virtual mesh (SURVEY.md §4 item 4).

Must run before jax is first imported anywhere in the test process — pytest
imports conftest.py before collecting test modules, which guarantees that.
The sharded-path tests use the same pjit/shard_map code paths as a real
TPU slice, just on emulated host devices.
"""

import os
import tempfile

# The shell environment pins JAX_PLATFORMS=axon (the TPU tunnel) and the
# plugin wins over a plain env override, so force CPU through the config
# API before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"

# Hermetic tuned-policy resolution: a policy.json persisted by a real
# `cli scenarios` run (next to the shared compile cache) must never
# leak into the suite's default-config rankings. Tests that exercise
# policy resolution point MICRORANK_POLICY_DIR at their own tmp dir.
os.environ.setdefault(
    "MICRORANK_POLICY_DIR", tempfile.mkdtemp(prefix="mr-policy-test-")
)

# Hermetic jit cache + warmup manifest: serve/stream dispatches record
# production pad-bucket shapes into the manifest next to the compile
# cache (shape-faithful warmup); pointing the suite at its own tmp dir
# keeps a developer's real ~/.cache manifest out of warmup-count pins
# and test shapes out of the real manifest.
os.environ.setdefault(
    "MICRORANK_JIT_CACHE", tempfile.mkdtemp(prefix="mr-jit-test-")
)
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from microrank_tpu.config import DetectorConfig  # noqa: E402
from microrank_tpu.detect import compute_slo, detect_numpy  # noqa: E402
from microrank_tpu.graph import build_detect_batch  # noqa: E402
from microrank_tpu.testing import SyntheticConfig, generate_case  # noqa: E402


def partition_case(case, detector_cfg: DetectorConfig = DetectorConfig()):
    """Shared detect+partition step: returns (normal_ids, abnormal_ids)."""
    vocab, baseline = compute_slo(case.normal)
    batch, trace_ids = build_detect_batch(case.abnormal, vocab)
    res = detect_numpy(batch, baseline, detector_cfg)
    abn = [t for t, a in zip(trace_ids, res.abnormal) if a]
    nrm = [
        t for t, a, v in zip(trace_ids, res.abnormal, res.valid) if v and not a
    ]
    return nrm, abn


@pytest.fixture(scope="session")
def small_case():
    """A small synthetic chaos case shared across tests."""
    return generate_case(SyntheticConfig(n_operations=24, n_traces=120, seed=7))


@pytest.fixture(scope="session")
def pod_case():
    """Instance-level case: 2 pods per service, fault on one pod."""
    return generate_case(
        SyntheticConfig(n_operations=16, n_pods=2, n_traces=160, seed=11)
    )
