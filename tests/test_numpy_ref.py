"""Golden tests for the oracle backend on a hand-computed toy graph.

Graph (instance-level op names A..D, traces t1..t3):
  call edges per trace:  t1: A->B, B->C   t2: A->D   t3: A->B, B->C
  coverage:              t1: {A,B,C}      t2: {A,D}  t3: {A,B,C}

Hand-derived reference matrices (pagerank.py:35-52 semantics):
  operation_operation: A: [B,B,D] (3 call-edge instances), B: [C,C], C/D: []
  p_ss[B,A] = p_ss[D,A] = 1/3, p_ss[C,B] = 1/2
  p_sr columns: t1 = t3 = (A,B,C @ 1/3), t2 = (A,D @ 1/2)
  p_rs rows:    t1 = t3 = (A:1/3, B:1/2, C:1/2), t2 = (A:1/3, D:1)
  kinds: t1,t3 -> 2; t2 -> 1
  normal preference: inv_kind=(1/2,1,1/2), sum=2 -> pr=(0.25, 0.5, 0.25)
"""

import numpy as np
import pytest

from microrank_tpu.config import PageRankConfig, SpectrumConfig
from microrank_tpu.rank_backends import numpy_ref

OO = {"A": ["B", "B", "D"], "B": ["C", "C"], "C": [], "D": []}
OT = {"t1": ["A", "B", "C"], "t2": ["A", "D"], "t3": ["A", "B", "C"]}
TO = {"A": ["t1", "t2", "t3"], "B": ["t1", "t3"], "C": ["t1", "t3"], "D": ["t2"]}
PR = {k: list(v) for k, v in OT.items()}


def test_matrices_golden():
    p_ss, p_sr, p_rs, nodes, traces = numpy_ref.build_matrices(OO, OT, TO)
    ni = {n: i for i, n in enumerate(nodes)}
    ti = {t: i for i, t in enumerate(traces)}
    exp_ss = np.zeros((4, 4), dtype=np.float32)
    exp_ss[ni["B"], ni["A"]] = 1 / 3
    exp_ss[ni["D"], ni["A"]] = 1 / 3
    exp_ss[ni["C"], ni["B"]] = 1 / 2
    np.testing.assert_array_equal(p_ss, exp_ss)

    exp_sr = np.zeros((4, 3), dtype=np.float32)
    for t in ("t1", "t3"):
        for op in ("A", "B", "C"):
            exp_sr[ni[op], ti[t]] = 1 / 3
    for op in ("A", "D"):
        exp_sr[ni[op], ti["t2"]] = 1 / 2
    np.testing.assert_array_equal(p_sr, exp_sr)

    exp_rs = np.zeros((3, 4), dtype=np.float32)
    for t in ("t1", "t3"):
        exp_rs[ti[t], ni["A"]] = 1 / 3
        exp_rs[ti[t], ni["B"]] = 1 / 2
        exp_rs[ti[t], ni["C"]] = 1 / 2
    exp_rs[ti["t2"], ni["A"]] = 1 / 3
    exp_rs[ti["t2"], ni["D"]] = 1.0
    np.testing.assert_array_equal(p_rs, exp_rs)


def test_kind_list_golden():
    _, p_sr, _, _, traces = numpy_ref.build_matrices(OO, OT, TO)
    kind = numpy_ref.compute_kind_list(p_sr)
    ti = {t: i for i, t in enumerate(traces)}
    assert kind[ti["t1"]] == 2 and kind[ti["t3"]] == 2 and kind[ti["t2"]] == 1


def test_normal_preference_golden():
    _, p_sr, _, _, traces = numpy_ref.build_matrices(OO, OT, TO)
    kind = numpy_ref.compute_kind_list(p_sr)
    ti = {t: i for i, t in enumerate(traces)}
    pr = numpy_ref._preference_vector(ti, PR, kind, False, PageRankConfig())
    np.testing.assert_allclose(pr[ti["t1"], 0], 0.25, rtol=1e-6)
    np.testing.assert_allclose(pr[ti["t2"], 0], 0.5, rtol=1e-6)
    np.testing.assert_allclose(pr[ti["t3"], 0], 0.25, rtol=1e-6)


def test_anomalous_preference_reference_form():
    # pr[t] = phi / num_sum / (kind_t/kind_sum*phi + 1/n_t)
    # kind_sum = 1/2 + 1 + 1/2 = 2 ; num_sum = 1/3 + 1/2 + 1/3 = 7/6
    _, p_sr, _, _, traces = numpy_ref.build_matrices(OO, OT, TO)
    kind = numpy_ref.compute_kind_list(p_sr)
    ti = {t: i for i, t in enumerate(traces)}
    pr = numpy_ref._preference_vector(ti, PR, kind, True, PageRankConfig())
    num_sum = 7 / 6
    exp_t1 = 0.5 / num_sum / (2 / 2 * 0.5 + 1 / 3)
    exp_t2 = 0.5 / num_sum / (1 / 2 * 0.5 + 1 / 2)
    np.testing.assert_allclose(pr[ti["t1"], 0], exp_t1, rtol=1e-6)
    np.testing.assert_allclose(pr[ti["t3"], 0], exp_t1, rtol=1e-6)
    np.testing.assert_allclose(pr[ti["t2"], 0], exp_t2, rtol=1e-6)


def test_paper_preference_eq7():
    # Eq (7): phi * (1/n_t)/num_sum + (1-phi) * (1/kind_t)/kind_sum
    _, p_sr, _, _, traces = numpy_ref.build_matrices(OO, OT, TO)
    kind = numpy_ref.compute_kind_list(p_sr)
    ti = {t: i for i, t in enumerate(traces)}
    cfg = PageRankConfig(preference="paper")
    pr = numpy_ref._preference_vector(ti, PR, kind, True, cfg)
    num_sum, kind_sum = 7 / 6, 2.0
    exp_t2 = 0.5 * (1 / 2) / num_sum + 0.5 * 1.0 / kind_sum
    np.testing.assert_allclose(pr[ti["t2"], 0], exp_t2, rtol=1e-6)
    # Paper form is a proper distribution: sums to 1.
    np.testing.assert_allclose(pr.sum(), 1.0, rtol=1e-6)


def test_power_iteration_properties():
    weight, trace_num = numpy_ref.trace_pagerank(OO, OT, TO, PR, False)
    assert set(weight) == {"A", "B", "C", "D"}
    assert trace_num == {"A": 3, "B": 2, "C": 2, "D": 1}
    assert all(w > 0 for w in weight.values())
    # A is covered by every trace and called most -> highest score.
    assert max(weight, key=weight.get) == "A"


def test_spectrum_golden_dstar2():
    # Hand-built spectrum cells.
    a_res = {"A": 1.0, "B": 0.5}
    n_res = {"A": 0.8, "C": 0.2}
    a_num = {"A": 4, "B": 2}
    n_num = {"A": 5, "C": 3}
    top, scores = numpy_ref.calculate_spectrum(
        a_res, n_res, 4, 6, n_num, a_num, SpectrumConfig(method="dstar2")
    )
    # A: ef=4, nf=0, ep=4.0 -> 16/4 = 4
    # B: ef=1, nf=1, ep=eps -> 1/(1+1e-7)
    # C: only-normal: ep=(1+0.2)*3, ef=nf=eps -> ~eps^2/3.6
    d = dict(zip(top, scores))
    np.testing.assert_allclose(d["A"], 4.0, rtol=1e-6)
    np.testing.assert_allclose(d["B"], 1 / (1 + 1e-7), rtol=1e-6)
    assert d["C"] < 1e-10
    assert top[0] == "A" and top[1] == "B"


@pytest.mark.parametrize(
    "method",
    ["ochiai", "jaccard", "tarantula", "russellrao", "m1", "m2",
     "goodman", "hamann", "dice", "sorensendice", "simplematcing", "rogers"],
)
def test_all_methods_finite(method):
    a_res = {"A": 1.0, "B": 0.5}
    n_res = {"A": 0.8, "C": 0.2}
    a_num = {"A": 4, "B": 2}
    n_num = {"A": 5, "C": 3}
    top, scores = numpy_ref.calculate_spectrum(
        a_res, n_res, 4, 6, n_num, a_num, SpectrumConfig(method=method)
    )
    assert len(top) == 3
    assert all(np.isfinite(s) for s in scores)
