"""Ground-truth parity: run the ACTUAL reference implementation.

The upstream MicroRank source is mounted read-only at /root/reference in
this environment. These tests import it (never copy it), drive its
component functions on synthetic data, and assert our oracle backend and
device backend reproduce its outputs — SLO dicts, partitions, PageRank
weights, spectrum rankings — to float tolerance. Skipped cleanly when the
mount is absent.
"""

import math
import sys
from pathlib import Path

import numpy as np
import pytest

REF = Path("/root/reference")
if not (REF / "pagerank.py").exists():
    pytest.skip("reference mount not available", allow_module_level=True)

sys.path.insert(0, str(REF))
import anormaly_detector as ref_detector  # noqa: E402
import online_rca as ref_rca  # noqa: E402
import pagerank as ref_pagerank  # noqa: E402
import preprocess_data as ref_pre  # noqa: E402

from microrank_tpu.config import MicroRankConfig  # noqa: E402
from microrank_tpu.detect import compute_slo, detect_numpy, slo_as_dict  # noqa: E402
from microrank_tpu.graph import (  # noqa: E402
    build_detect_batch,
    pagerank_graph_dicts,
)
from microrank_tpu.rank_backends import NumpyRefBackend, numpy_ref  # noqa: E402
from microrank_tpu.rank_backends.jax_tpu import JaxBackend  # noqa: E402
from microrank_tpu.testing import SyntheticConfig, generate_case  # noqa: E402


@pytest.fixture(scope="module")
def case():
    return generate_case(
        SyntheticConfig(
            n_operations=18, n_traces=150, seed=21, n_kinds=16,
            child_keep_prob=0.6,
        )
    )


def test_slo_matches_reference(case):
    ref_df = case.normal.copy()
    ref_ops = ref_pre.get_service_operation_list(ref_df)
    ref_slo = ref_pre.get_operation_slo(ref_ops, ref_df)

    vocab, baseline = compute_slo(case.normal)
    ours = slo_as_dict(vocab, baseline)
    assert set(ours) == set(ref_slo)
    for op, (mean, std) in ref_slo.items():
        assert ours[op][0] == pytest.approx(mean, abs=2e-4), op
        assert ours[op][1] == pytest.approx(std, abs=2e-4), op


def _reference_partition(case):
    ref_norm = case.normal.copy()
    ops = ref_pre.get_service_operation_list(ref_norm)
    slo = ref_pre.get_operation_slo(ops, ref_norm)
    out = ref_detector.system_anomaly_detect(
        case.abnormal.copy(),
        case.abnormal["startTime"].min(),
        case.abnormal["endTime"].max(),
        slo,
        ops,
    )
    assert out is not False, "reference found the window empty"
    flag, abnormal, normal = out
    return flag, abnormal, normal


def test_detection_partition_matches_reference(case):
    flag, ref_abn, ref_nrm = _reference_partition(case)

    vocab, baseline = compute_slo(case.normal)
    batch, trace_ids = build_detect_batch(case.abnormal, vocab)
    det = detect_numpy(batch, baseline, MicroRankConfig().detector)
    abn = {t for t, a in zip(trace_ids, det.abnormal) if a}
    nrm = {
        t
        for t, a, v in zip(trace_ids, det.abnormal, det.valid)
        if v and not a
    }
    assert bool(det.flag) == bool(flag)
    assert abn == set(ref_abn)
    assert nrm == set(ref_nrm)


def test_graph_dicts_match_reference(case):
    _, ref_abn, _ = _reference_partition(case)
    ref_graph = ref_pre.get_pagerank_graph(ref_abn, case.abnormal.copy())
    ours = pagerank_graph_dicts(ref_abn, case.abnormal)
    for i, name in enumerate(
        ["operation_operation", "operation_trace", "trace_operation", "pr_trace"]
    ):
        assert set(ours[i]) == set(ref_graph[i]), name
        for k in ref_graph[i]:
            assert sorted(ours[i][k]) == sorted(ref_graph[i][k]), (name, k)


def test_trace_pagerank_matches_reference(case):
    _, ref_abn, ref_nrm = _reference_partition(case)
    for trace_list, anomaly in ((ref_nrm, False), (ref_abn, True)):
        graph = ref_pre.get_pagerank_graph(trace_list, case.abnormal.copy())
        ref_weight, ref_num = ref_pagerank.trace_pagerank(*graph, anomaly)
        our_weight, our_num = numpy_ref.trace_pagerank(*graph, anomaly)
        assert our_num == ref_num
        assert set(our_weight) == set(ref_weight)
        for op in ref_weight:
            assert our_weight[op] == pytest.approx(
                ref_weight[op], rel=1e-9
            ), op


def test_full_rca_matches_reference(case):
    """End-to-end: the reference orchestrator's exact computation (with
    its partition swap, online_rca.py:167) vs our reference_compat path —
    oracle bit-close, device backend to f32 tolerance."""
    flag, ref_abn, ref_nrm = _reference_partition(case)
    # Reproduce the orchestrator unpack swap: downstream 'normal_list' is
    # the returned abnormal list and vice versa.
    normal_list, abnormal_list = ref_abn, ref_nrm

    graph_n = ref_pre.get_pagerank_graph(normal_list, case.abnormal.copy())
    normal_result, normal_num = ref_pagerank.trace_pagerank(*graph_n, False)
    graph_a = ref_pre.get_pagerank_graph(abnormal_list, case.abnormal.copy())
    anomaly_result, anomaly_num = ref_pagerank.trace_pagerank(*graph_a, True)
    ref_top, ref_scores = ref_rca.calculate_spectrum_without_delay_list(
        anomaly_result=anomaly_result,
        normal_result=normal_result,
        anomaly_list_len=len(abnormal_list),
        normal_list_len=len(normal_list),
        top_max=5,
        normal_num_list=normal_num,
        anomaly_num_list=anomaly_num,
        spectrum_method="dstar2",
    )

    cfg = MicroRankConfig.reference_compat()
    # Backends take (normal, abnormal) verbatim; the swap is encoded in
    # the lists above, exactly as the reference orchestrator's unpack
    # produced them (the pipeline's compat.partition_swap flag does the
    # same inversion before reaching the backend).
    oracle_top, oracle_scores = NumpyRefBackend(cfg).rank_window(
        case.abnormal, normal_list, abnormal_list
    )
    assert oracle_top == ref_top
    np.testing.assert_allclose(oracle_scores, ref_scores, rtol=1e-9)

    # Pin the f32 kernel: the tight 2e-3 score comparison against the
    # reference's float64 computation leaves no room for the default
    # bf16 auto kernel's rounding (rank parity under bf16 is covered by
    # the backend-parity suite).
    import dataclasses

    cfg_f32 = cfg.replace(
        runtime=dataclasses.replace(cfg.runtime, prefer_bf16=False)
    )
    jax_top, jax_scores = JaxBackend(cfg_f32).rank_window(
        case.abnormal, normal_list, abnormal_list
    )
    assert jax_top[0] == ref_top[0]
    assert set(jax_top) == set(ref_top)
    ref_map = dict(zip(ref_top, ref_scores))
    for name, score in zip(jax_top, jax_scores):
        assert score == pytest.approx(ref_map[name], rel=2e-3), name


def test_trace_list_partition_matches_reference(case):
    """C6: the alternate 1-sigma + 50ms path (trace_anormaly_detect /
    trace_list_partition, anormaly_detector.py:101-139) vs our unified
    detector with DetectorConfig.single_trace_variant()."""
    from microrank_tpu.config import DetectorConfig

    ref_norm = case.normal.copy()
    ops = ref_pre.get_service_operation_list(ref_norm)
    slo = ref_pre.get_operation_slo(ops, ref_norm)
    operation_count = ref_pre.get_operation_duration_data(
        ops, case.abnormal.copy()
    )
    ref_abn, ref_nrm = ref_detector.trace_list_partition(operation_count, slo)

    vocab, baseline = compute_slo(case.normal)
    batch, trace_ids = build_detect_batch(case.abnormal, vocab)
    det = detect_numpy(batch, baseline, DetectorConfig.single_trace_variant())
    abn = {t for t, a in zip(trace_ids, det.abnormal) if a}
    # The reference path has no duration>0 validity filter in the
    # partition loop itself (it inherits it from
    # get_operation_duration_data's dropna/positive filter) — compare on
    # the traces it actually scored.
    scored = set(operation_count)
    ours_abn = abn & scored
    assert ours_abn == set(ref_abn)


# --- Vendored OTel-demo-shaped fixture (tests/data/otel_demo) ---------
#
# Raw ClickHouse-contract CSVs carrying the real-data quirks the
# synthetic perf generator never produces: out-of-order rows, orphan
# ParentSpanIds, a duplicate SpanId (normal window only — see
# make_fixture.py for why), comma-bearing quoted SpanNames, hex ids.
# The golden claim: the FULL pipeline (loader -> SLO -> detection ->
# partition -> PageRank -> spectrum) reproduces the actual reference
# implementation on this messy input, on both ingest lanes.

FIXTURE = Path(__file__).parent / "data" / "otel_demo"
FAULT_OP = (
    "paymentservice-3f4a5b6c7d-qy7hz_oteldemo.PaymentService/Charge"
)


@pytest.fixture(scope="module")
def otel_frames():
    from microrank_tpu.io import load_traces_csv

    normal = load_traces_csv(FIXTURE / "normal.csv")
    abnormal = load_traces_csv(FIXTURE / "abnormal.csv")
    return normal, abnormal


def test_otel_fixture_quirks_present(otel_frames):
    """The fixture actually carries the quirks it claims (guards the
    committed CSVs against a regenerate that loses them)."""
    normal, abnormal = otel_frames
    # Out-of-order rows.
    assert not normal["startTime"].is_monotonic_increasing
    assert not abnormal["startTime"].is_monotonic_increasing
    # Duplicate SpanId in the normal window only.
    assert normal["spanID"].duplicated().any()
    assert not abnormal["spanID"].duplicated().any()
    # Orphan parents: non-empty ParentSpanIds absent from the dump.
    known = set(abnormal["spanID"])
    parents = abnormal["ParentSpanId"].fillna("")
    orphans = [p for p in parents if p and p not in known]
    assert len(orphans) > 0
    # Comma-bearing span name survived CSV quoting.
    assert any("," in n for n in abnormal["operationName"])


def test_otel_fixture_full_rca_matches_reference(otel_frames):
    """End-to-end golden parity on the messy fixture: reference SLO +
    detection + partition + PageRank + spectrum vs our oracle (bit-close,
    insertion tie order) and device backend (f32 tolerance).

    Localization note: every anomalous trace here is a checkout request,
    so the checkout-exclusive ops (PlaceOrder, ShipOrder, email,
    EmptyCart, Charge) share IDENTICAL coverage spectra and tie at the
    top — a genuine property of coverage-spectrum ranking on
    single-request-kind faults, reproduced exactly by the reference on
    this same file. The golden claim is parity; the accuracy claim is
    the paper-style fault-in-top-5 (its own single-fault R@1 is 94%,
    not 100%)."""
    normal, abnormal = otel_frames

    ops = ref_pre.get_service_operation_list(normal.copy())
    slo = ref_pre.get_operation_slo(ops, normal.copy())
    out = ref_detector.system_anomaly_detect(
        abnormal.copy(),
        abnormal["startTime"].min(),
        abnormal["endTime"].max(),
        slo,
        ops,
    )
    assert out is not False
    flag, ref_abn, ref_nrm = out
    assert flag

    # Our detection partitions identically on the messy input.
    vocab, baseline = compute_slo(normal)
    batch, trace_ids = build_detect_batch(abnormal, vocab)
    det = detect_numpy(batch, baseline, MicroRankConfig().detector)
    abn = {t for t, a in zip(trace_ids, det.abnormal) if a}
    nrm = {
        t
        for t, a, v in zip(trace_ids, det.abnormal, det.valid)
        if v and not a
    }
    assert abn == set(ref_abn)
    assert nrm == set(ref_nrm)

    graph_n = ref_pre.get_pagerank_graph(ref_nrm, abnormal.copy())
    normal_result, normal_num = ref_pagerank.trace_pagerank(*graph_n, False)
    graph_a = ref_pre.get_pagerank_graph(ref_abn, abnormal.copy())
    anomaly_result, anomaly_num = ref_pagerank.trace_pagerank(*graph_a, True)
    ref_top, ref_scores = ref_rca.calculate_spectrum_without_delay_list(
        anomaly_result=anomaly_result,
        normal_result=normal_result,
        anomaly_list_len=len(ref_abn),
        normal_list_len=len(ref_nrm),
        top_max=5,
        normal_num_list=normal_num,
        anomaly_num_list=anomaly_num,
        spectrum_method="dstar2",
    )
    assert FAULT_OP in ref_top[:5]

    import dataclasses

    from microrank_tpu.config import SpectrumConfig

    # Insertion tie order for the oracle: the tied checkout-exclusive
    # block must come out in the reference's exact (dict-order) sequence
    # for a positional comparison.
    cfg_ins = MicroRankConfig(
        spectrum=SpectrumConfig(tiebreak="insertion")
    )
    oracle_top, oracle_scores = NumpyRefBackend(cfg_ins).rank_window(
        abnormal, list(ref_nrm), list(ref_abn)
    )
    assert oracle_top == ref_top
    np.testing.assert_allclose(oracle_scores, ref_scores, rtol=1e-9)

    cfg_f32 = MicroRankConfig()
    cfg_f32 = cfg_f32.replace(
        runtime=dataclasses.replace(cfg_f32.runtime, prefer_bf16=False)
    )
    jax_top, jax_scores = JaxBackend(cfg_f32).rank_window(
        abnormal, list(ref_nrm), list(ref_abn)
    )
    assert FAULT_OP in jax_top[:5]
    assert set(jax_top) == set(ref_top)
    ref_map = dict(zip(ref_top, ref_scores))
    for name, score in zip(jax_top, jax_scores):
        assert score == pytest.approx(ref_map[name], rel=2e-3), name


def test_otel_fixture_native_lane_matches_pandas(otel_frames, tmp_path):
    """The C++ ingest lane ranks the messy fixture identically to the
    pandas lane (duplicate SpanId, orphans and quoting included), with
    the kind collapse active."""
    from microrank_tpu.native import load_span_table
    from microrank_tpu.pipeline.runner import OnlineRCA
    from microrank_tpu.pipeline.table_runner import TableRCA

    cfg = MicroRankConfig()
    rca_t = TableRCA(cfg)
    # cache=False: never drop sidecar .npz files into the committed
    # fixture directory.
    rca_t.fit_baseline(load_span_table(FIXTURE / "normal.csv", cache=False))
    res_t = rca_t.run(load_span_table(FIXTURE / "abnormal.csv", cache=False))
    ranked_t = [r for r in res_t if r.ranking]
    assert ranked_t, "native lane ranked no window"
    top_t = [n for n, _ in ranked_t[0].ranking]
    assert FAULT_OP in top_t[:5]

    rca_p = OnlineRCA(cfg)
    normal, abnormal = otel_frames
    rca_p.fit_baseline(normal)
    res_p = rca_p.run(abnormal)
    ranked_p = [r for r in res_p if r.ranking]
    assert ranked_p, "pandas lane ranked no window"
    assert [n for n, _ in ranked_p[0].ranking] == top_t
