"""Generate the vendored OTel-demo-shaped trace fixture (run once; the
CSVs are committed).

Provenance: this environment has no network egress, so a genuine
opentelemetry-demo ClickHouse dump cannot be fetched or recorded. This
fixture is the honest offline substitute: spans shaped like the PUBLIC
opentelemetry-demo architecture (frontend -> checkout -> payment /
email / shipping -> quote, cart, product-catalog, recommendation, ad,
currency — the well-known Astronomy-Shop call graph), exported in the
EXACT raw ClickHouse CSV contract the reference's collect_data.py
produces (`Timestamp, TraceId, SpanId, ParentSpanId, SpanName,
ServiceName, PodName, Duration, SpanKind, TraceStart, TraceEnd`;
Duration in microseconds, trace-level start/end datetimes), and
carrying the REAL-DATA QUIRKS the synthetic perf generator never
exercises:

* rows shuffled out of time order (exports are not time-sorted);
* ~2% orphan ParentSpanIds (parents sampled out of the export — both
  the reference's merge linkage and our positional lookup must drop
  the edge, not crash);
* one duplicated SpanId across two different spans (IN THE NORMAL
  WINDOW ONLY: the SLO baseline never reads linkage, so the documented
  positional-vs-merge deviation — graph/build.py:22-26 — cannot
  perturb the golden ranking comparison, while the loader still has to
  survive the duplicate);
* a SpanName containing a comma + quotes (CSV quoting path);
* 128-bit hex TraceIds / 64-bit hex SpanIds, k8s-style pod names.

The abnormal window injects +1800 ms into paymentservice Charge; the
latency propagates up checkout -> frontend inclusively, exactly like a
real payment outage. tests/test_reference_golden.py golden-tests the
full detect -> partition -> rank pipeline on these files against the
actual reference implementation.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pandas as pd

HERE = Path(__file__).parent

# (service, operation) tree per trace kind: list of (idx, parent_idx,
# service, span name). idx 0 is the root. Shapes follow the public
# opentelemetry-demo (Astronomy Shop) request flows.
KINDS = {
    "home": [
        (0, -1, "frontend", "GET /"),
        (1, 0, "frontend", "grpc.oteldemo.ProductCatalogService/ListProducts"),
        (2, 1, "productcatalogservice", "oteldemo.ProductCatalogService/ListProducts"),
        (3, 0, "frontend", "grpc.oteldemo.RecommendationService/ListRecommendations"),
        (4, 3, "recommendationservice", "oteldemo.RecommendationService/ListRecommendations"),
        (5, 4, "productcatalogservice", "oteldemo.ProductCatalogService/GetProduct"),
        (6, 0, "frontend", "grpc.oteldemo.AdService/GetAds"),
        (7, 6, "adservice", "oteldemo.AdService/GetAds"),
        (8, 0, "frontend", "grpc.oteldemo.CurrencyService/GetSupportedCurrencies"),
        (9, 8, "currencyservice", "oteldemo.CurrencyService/GetSupportedCurrencies"),
    ],
    "product": [
        (0, -1, "frontend", "GET /api/products/{id}"),
        (1, 0, "productcatalogservice", "oteldemo.ProductCatalogService/GetProduct"),
        (2, 0, "frontend", "grpc.oteldemo.RecommendationService/ListRecommendations"),
        (3, 2, "recommendationservice", "oteldemo.RecommendationService/ListRecommendations"),
        (4, 3, "productcatalogservice", "oteldemo.ProductCatalogService/GetProduct"),
        (5, 0, "currencyservice", "oteldemo.CurrencyService/Convert"),
        (6, 0, "adservice", "oteldemo.AdService/GetAds"),
    ],
    # The cart page also fetches a shipping estimate (shipping -> quote),
    # like the demo's /api/shipping flow — so shipping/quote ops appear
    # in a NON-checkout kind too, the way they do in the real system.
    "cart": [
        (0, -1, "frontend", "POST /api/cart"),
        (1, 0, "cartservice", "oteldemo.CartService/AddItem"),
        (2, 0, "productcatalogservice", "oteldemo.ProductCatalogService/GetProduct"),
        (3, 0, "cartservice", "oteldemo.CartService/GetCart"),
        (4, 0, "shippingservice", "oteldemo.ShippingService/GetQuote"),
        (5, 4, "quoteservice", "CalculateQuote"),
        (6, 0, "currencyservice", "oteldemo.CurrencyService/Convert"),
    ],
    # The SpanName with a comma exercises CSV quoting end to end.
    "compare": [
        (0, -1, "frontend", 'GET /api/products?ids=1,2,3'),
        (1, 0, "productcatalogservice", "oteldemo.ProductCatalogService/GetProduct"),
        (2, 0, "currencyservice", "oteldemo.CurrencyService/Convert"),
    ],
    "checkout": [
        (0, -1, "frontend", "POST /api/checkout"),
        (1, 0, "checkoutservice", "oteldemo.CheckoutService/PlaceOrder"),
        (2, 1, "cartservice", "oteldemo.CartService/GetCart"),
        (3, 1, "productcatalogservice", "oteldemo.ProductCatalogService/GetProduct"),
        (4, 1, "currencyservice", "oteldemo.CurrencyService/Convert"),
        (5, 1, "shippingservice", "oteldemo.ShippingService/GetQuote"),
        (6, 5, "quoteservice", "CalculateQuote"),
        (7, 1, "paymentservice", "oteldemo.PaymentService/Charge"),
        (8, 1, "emailservice", "POST /send_order_confirmation"),
        (9, 1, "shippingservice", "oteldemo.ShippingService/ShipOrder"),
        (10, 1, "cartservice", "oteldemo.CartService/EmptyCart"),
    ],
}

KIND_WEIGHTS = {"home": 0.3, "product": 0.3, "cart": 0.15,
                "compare": 0.05, "checkout": 0.2}

# Mean own-time (ms) per service (lognormal sigma 0.35 around these).
MEAN_OWN_MS = {
    "frontend": 4.0, "productcatalogservice": 2.0,
    "recommendationservice": 3.0, "adservice": 2.5,
    "currencyservice": 1.0, "cartservice": 2.0, "checkoutservice": 5.0,
    "shippingservice": 2.5, "quoteservice": 1.5, "paymentservice": 6.0,
    "emailservice": 4.0,
}

POD = {
    s: f"{s}-{h}"
    for s, h in {
        "frontend": "7d9f8c6b5-x2v4q",
        "productcatalogservice": "5f6d8b9c44-mq7zl",
        "recommendationservice": "6c8d7f9b55-kp3wn",
        "adservice": "84c5f6d7e8-rt2vx",
        "currencyservice": "9b8a7c6d5e-fh4jk",
        "cartservice": "4e5f6a7b8c-zw9qm",
        "checkoutservice": "7a8b9c0d1e-ns6tp",
        "shippingservice": "2c3d4e5f6a-gb8vr",
        "quoteservice": "8d9e0f1a2b-lm5cx",
        "paymentservice": "3f4a5b6c7d-qy7hz",
        "emailservice": "5a6b7c8d9e-dk2jw",
    }.items()
}

FAULT_SERVICE = "paymentservice"
FAULT_LATENCY_MS = 1800.0


def _hex(rng: np.random.Generator, n: int) -> str:
    return "".join(rng.choice(list("0123456789abcdef"), size=n))


def _render_window(
    rng: np.random.Generator,
    n_traces: int,
    t0: pd.Timestamp,
    window_minutes: float,
    faulted: bool,
) -> pd.DataFrame:
    kinds = list(KINDS)
    probs = np.array([KIND_WEIGHTS[k] for k in kinds])
    rows = []
    offsets = np.sort(rng.uniform(0, window_minutes * 60e6, size=n_traces))
    for ti in range(n_traces):
        kind = kinds[int(rng.choice(len(kinds), p=probs))]
        tree = KINDS[kind]
        trace_id = _hex(rng, 32)
        span_ids = [_hex(rng, 16) for _ in tree]
        own_ms = np.array(
            [
                rng.lognormal(np.log(MEAN_OWN_MS[svc]), 0.35)
                for _, _, svc, _ in tree
            ]
        )
        if faulted:
            for i, (_, _, svc, _) in enumerate(tree):
                if svc == FAULT_SERVICE:
                    own_ms[i] += FAULT_LATENCY_MS
        # Inclusive durations: deepest-first accumulation into parents.
        dur_ms = own_ms.copy()
        for i in range(len(tree) - 1, 0, -1):
            dur_ms[tree[i][1]] += dur_ms[i]
        start_us = int(offsets[ti])
        trace_start = t0 + pd.Timedelta(microseconds=start_us)
        trace_end = trace_start + pd.Timedelta(
            microseconds=float(dur_ms[0]) * 1000.0
        )
        for i, (idx, parent, svc, name) in enumerate(tree):
            parent_id = span_ids[parent] if parent >= 0 else ""
            # ~2% orphan parents: the parent span was sampled out of the
            # export — the id exists but its row does not.
            if parent >= 0 and rng.random() < 0.02:
                parent_id = _hex(rng, 16)
            rows.append(
                {
                    "Timestamp": trace_start,
                    "TraceId": trace_id,
                    "SpanId": span_ids[i],
                    "ParentSpanId": parent_id,
                    "SpanName": name,
                    "ServiceName": svc,
                    "PodName": POD[svc],
                    "Duration": int(round(dur_ms[i] * 1000.0)),  # µs
                    "SpanKind": "Server" if parent < 0 else "Client",
                    "TraceStart": trace_start,
                    "TraceEnd": trace_end,
                }
            )
    df = pd.DataFrame(rows)
    # Exports are not time-ordered: shuffle.
    return df.sample(frac=1.0, random_state=int(rng.integers(1 << 31)))


def main() -> None:
    rng = np.random.default_rng(20260730)
    t0 = pd.Timestamp("2026-03-01 09:00:00")
    t1 = t0 + pd.Timedelta(minutes=5)
    normal = _render_window(rng, 260, t0, 5.0, faulted=False)
    # Duplicate SpanId across two DIFFERENT spans, normal window only
    # (SLO reads no linkage, so this exercises the loader's documented
    # positional-match deviation without touching the ranked window).
    dup = normal.iloc[0].copy()
    victim = normal.index[5]
    normal.loc[victim, "SpanId"] = dup["SpanId"]
    abnormal = _render_window(rng, 260, t1, 5.0, faulted=True)
    normal.to_csv(HERE / "normal.csv", index=False)
    abnormal.to_csv(HERE / "abnormal.csv", index=False)
    print(
        f"wrote {len(normal)} normal + {len(abnormal)} abnormal spans; "
        f"fault: {POD[FAULT_SERVICE]}_{FAULT_SERVICE and 'oteldemo.PaymentService/Charge'}"
    )


if __name__ == "__main__":
    main()
