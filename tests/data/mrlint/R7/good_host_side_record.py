"""R7 negative: telemetry recorded on the HOST side, after the fetch —
the value entering the sink is a fetched numpy scalar, outside any
traced call graph."""

import jax
import jax.numpy as jnp


def kernel(x):
    return jnp.sum(x * 2)


kernel_jit = jax.jit(kernel)


class _Hist:
    def observe(self, v, **labels):
        return float(v)


def rank_and_record(host_array):
    out = kernel_jit(host_array)
    fetched = jax.device_get(out)
    _Hist().observe(float(fetched), stage="rank")
    return fetched
