"""R7 positive: a traced value passed as a journal event field inside a
jit region — the journal json.dumps()es every field on emit."""

import jax


class _Journal:
    def emit(self, event, **fields):
        return event, fields


_JOURNAL = _Journal()


def rank_core(graph, scores):
    top = scores.max()
    _JOURNAL.emit("window", top_score=top)
    return scores


rank_core_jit = jax.jit(rank_core)
