"""R7 positive: a traced array observed into a histogram inside a jit
region — the sink float()s it, a host sync laundered through the
telemetry layer."""

import jax


def _residual_hist():
    class _H:
        def observe(self, v, **labels):
            return float(v)

    return _H()


def rank_step(x):
    residual = x.sum()
    _residual_hist().observe(residual, stage="rank")
    return x * 2


rank_step_jit = jax.jit(rank_step)
