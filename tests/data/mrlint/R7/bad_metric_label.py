"""R7 positive: a traced value used as a metric LABEL (keyword) via a
``record_*`` helper inside a jit region — labels are strings on the
host; the helper str()s the tracer."""

import jax


def record_window_outcome(outcome):
    return str(outcome)


def detect_step(flags):
    n_abnormal = flags.sum()
    record_window_outcome(outcome=n_abnormal)
    return flags


detect_jit = jax.jit(detect_step)
