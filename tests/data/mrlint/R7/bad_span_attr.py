"""R7 positive: a traced value as a span attribute inside a jit region
— span attrs are host values the flight recorder json-serializes."""

import jax


class _Tracer:
    def span(self, name, **attrs):
        return name, attrs


_TRACER = _Tracer()


def dispatch_step(x):
    weight = x.mean()
    _TRACER.span("device_dispatch", weight=weight)
    return x * weight


dispatch_jit = jax.jit(dispatch_step)
