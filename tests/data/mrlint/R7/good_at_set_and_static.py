"""R7 negatives inside a jit region: the jax ``x.at[i].set(v)``
indexed-update idiom shares the ``set`` method name but is not a
telemetry sink (even with a traced operand), and static (shape-derived)
telemetry values never carry taint."""

import jax


class _Gauge:
    def set(self, v, **labels):
        return float(v)


_GAUGE = _Gauge()


def kernel(x, i):
    y = x.at[i].set(x[0] * 2.0)   # indexed update, not a sink
    _GAUGE.set(x.shape[0], axis="traces")  # static shape: no taint
    return y


kernel_jit = jax.jit(kernel)
