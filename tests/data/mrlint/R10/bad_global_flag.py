"""R10 bad: a module global rebound by a pool worker and read by the
caller with no common lock — the module owns a lock (for other state),
so its globals are in the race-checked set."""

import threading
from concurrent.futures import ThreadPoolExecutor

_state_lock = threading.Lock()
_last_result = None


def _work(x):
    global _last_result
    _last_result = x * 2


def run(pool_size=2):
    pool = ThreadPoolExecutor(pool_size)
    pool.submit(_work, 21)
    return _last_result
