"""R10 bad: a counter written on a spawned thread and read on the main
thread with no lock held at either access — the class owns a lock, it
just never guards this attribute."""

import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.windows = 0

    def loop(self):
        self.windows = self.windows + 1

    def start(self):
        t = threading.Thread(target=self.loop, name="engine")
        t.start()

    def stats(self):
        return self.windows
