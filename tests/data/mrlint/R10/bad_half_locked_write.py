"""R10 bad: the reader diligently takes the lock but the writer (on
the sink-callback thread class) does not — one unguarded side is
enough to empty the common lockset."""

import threading


class StatsSink:
    def __init__(self):
        self._lock = threading.Lock()
        self.emitted = 0

    def emit(self, event):
        self.emitted = self.emitted + 1

    def snapshot(self):
        with self._lock:
            return self.emitted
