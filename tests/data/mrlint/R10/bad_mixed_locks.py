"""R10 bad: writer and reader each hold a lock — but DIFFERENT locks,
so the intersection of the locksets is empty and the accesses still
race (the classic two-lock false-protection bug)."""

import threading


class Buffered:
    def __init__(self):
        self._write_lock = threading.Lock()
        self._read_lock = threading.Lock()
        self.pending = []

    def push(self, item):
        with self._write_lock:
            self.pending = self.pending + [item]

    def start(self):
        t = threading.Thread(target=self.drain)
        t.start()

    def drain(self):
        with self._read_lock:
            items = self.pending
            self.pending = []
        return items
