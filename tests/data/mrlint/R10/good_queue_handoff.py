"""R10 good: the safe queue-handoff seam — cross-thread data flows
through a queue.Queue attribute (internally synchronized), not through
bare shared attributes."""

import queue
import threading


class Producer:
    def __init__(self):
        self._lock = threading.Lock()   # guards other state
        self.q = queue.Queue()

    def produce(self):
        self.q.put("window")

    def start(self):
        t = threading.Thread(target=self.produce)
        t.start()

    def consume(self):
        return self.q.get(timeout=1.0)
