"""R10 good: an intentional lock-free publish (a monotonic stop flag)
routed through the ``published()`` marker — documented handoff, not a
finding."""

import threading

from microrank_tpu.utils.guards import published


class Engine:
    def __init__(self):
        self._lock = threading.Lock()   # guards other state
        self.stop = published(False)

    def request_stop(self):
        self.stop = published(True)

    def loop(self):
        while not self.stop:
            pass

    def start(self):
        t = threading.Thread(target=self.loop)
        t.start()
