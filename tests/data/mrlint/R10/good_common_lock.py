"""R10 good: every access — writer thread and main-thread reader —
holds the SAME lock, so the intersected lockset is non-empty."""

import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.windows = 0

    def loop(self):
        with self._lock:
            self.windows = self.windows + 1

    def start(self):
        t = threading.Thread(target=self.loop, name="engine")
        t.start()

    def stats(self):
        with self._lock:
            return self.windows
