"""R10 good: single-assignment-then-publish — the attribute is written
only in __init__ (before any thread can see the object) and read
cross-thread afterwards."""

import threading


class Engine:
    def __init__(self, config):
        self._lock = threading.Lock()
        self.config = config

    def loop(self):
        return self.config

    def start(self):
        t = threading.Thread(target=self.loop)
        t.start()
