"""R6 positive: jax.device_put inside a jitted function body."""

import jax
import jax.numpy as jnp


def rank_step(x):
    weights = jax.device_put(jnp.ones((4,)))  # traces to a hint, not a put
    return x * weights


rank_step_jit = jax.jit(rank_step)
