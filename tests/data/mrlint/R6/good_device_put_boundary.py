"""R6 negative: device_put at the dispatch boundary (host side), the
staged value passed INTO the jitted program as an argument."""

import jax
import jax.numpy as jnp


def kernel(x):
    return x * jnp.float32(2)


kernel_jit = jax.jit(kernel)


def stage_and_dispatch(host_array):
    staged = jax.device_put(host_array)  # real transfer, outside any trace
    return kernel_jit(staged)
