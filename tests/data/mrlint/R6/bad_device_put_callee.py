"""R6 positive: device_put in a helper REACHED from a jit root (the
traced-call-graph propagation must see through the call)."""

import jax
import jax.numpy as jnp


def _stage_inner(v):
    return jax.device_put(v) + jnp.float32(1)


def kernel(x):
    return _stage_inner(x) * 2


kernel_jit = jax.jit(kernel)
