"""R15 negative: same escape, suppressed with a justified pragma on
the seam call's first line (covers the continuation lines too)."""
import numpy as np


def serve(table, pagerank_cfg, spectrum_cfg):
    n = len(table)
    graph = np.zeros((n, n), dtype=np.float32)
    # mrlint: disable=R15(fixture: one-shot offline audit path, recompiles are acceptable)
    return stage_rank_window(
        graph, pagerank_cfg, spectrum_cfg, "kind", True
    )
