"""R15 positive: an array shaped by a raw host measurement reaches a
dispatch seam — its shape keys the compile cache outside the pad-bucket
registry (one compiled program per distinct window)."""
import numpy as np


def serve(table, pagerank_cfg, spectrum_cfg):
    n = len(table)
    graph = np.zeros((n, n), dtype=np.float32)
    return stage_rank_window(
        graph, pagerank_cfg, spectrum_cfg, "kind", True
    )
