"""R1 true positive (method-call laundering): a local bound to a sync
method of a traced value syncs when CALLED, not where it was bound."""
import jax


def f(x):
    grab = x.item  # binds the sync; no sync yet
    limit = grab()  # the laundered host sync happens here
    return x * limit


f_jit = jax.jit(f)
