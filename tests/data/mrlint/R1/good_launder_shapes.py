"""R1 true negative: the laundering shapes over STATIC operands — a
functools.reduce over shape dims, a bound method of a host list, and
math on a static size — are ordinary host code inside a traced fn."""
import functools
import math
import operator

import jax
import jax.numpy as jnp


def f(x, dims):
    n = functools.reduce(operator.mul, x.shape)  # shapes are static
    grab = [1, 2, 3].count  # bound method of a host value
    k = grab(2)
    m = math.sqrt(float(n))  # static operand: fine
    return jnp.sum(x) / (n + k + m)


f_jit = jax.jit(f, static_argnames=("dims",))
