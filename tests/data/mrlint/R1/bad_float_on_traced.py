"""R1 true positive: float() on a traced value inside a jitted function."""
import jax


def scale_by_host(x):
    s = float(x)  # host sync on a tracer
    return x * s


scale_jit = jax.jit(scale_by_host)
