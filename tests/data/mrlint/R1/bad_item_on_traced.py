"""R1 true positive: .item() on a traced value inside a decorated jit."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=(1,))
def pick(x, mode):
    limit = x.max().item()  # device->host sync per call
    return jnp.clip(x, 0.0, limit)
