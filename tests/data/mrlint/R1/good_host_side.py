"""R1 true negative: the same host conversions OUTSIDE any traced graph,
plus static .shape/int() use INSIDE one."""
import jax
import jax.numpy as jnp
import numpy as np


def traced(x, cfg):
    n = int(x.shape[0])  # shapes are static under tracing — fine
    return jnp.sum(x) / n


traced_jit = jax.jit(traced, static_argnames=("cfg",))


def host_fetch(x):
    # Not jitted, not called from a traced function: float()/np.asarray
    # here are ordinary host code.
    arr = np.asarray(x)
    return float(arr.sum())
