"""R1 true positive (stop_gradient-style laundering through a "static"
module): functools.reduce over a traced value yields a traced value —
the walker once treated every functools/math/dataclasses call as
host-static, so the float() below escaped the scalarizer check."""
import functools
import operator

import jax


def f(x):
    total = functools.reduce(operator.add, x)
    return x * float(total)  # host sync on the laundered tracer


f_jit = jax.jit(f)
