"""R1 true positive: np.asarray on a traced value, reached transitively
(the jitted root calls a helper that concretizes its argument)."""
import jax
import jax.numpy as jnp
import numpy as np


def _helper(v):
    host = np.asarray(v)  # concretizes the tracer
    return jnp.asarray(host.sum())


def entry(x):
    return _helper(x * 2.0)


entry_jit = jax.jit(entry)
