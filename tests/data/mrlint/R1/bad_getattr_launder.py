"""R1 true positive (getattr laundering): getattr(x, "tolist")() is the
same host sync as x.tolist() — the string spelling must not hide it."""
import jax


def f(x):
    vals = getattr(x, "tolist")()
    return len(vals) * x


f_jit = jax.jit(f)
