"""R14 positive: f32 and bf16 arrays meet at one fused program
boundary with no explicit cast — XLA places the upcast inside the
fusion, drifting accumulation precision between call sites."""
import jax
import jax.numpy as jnp
import numpy as np


def combine(a, b):
    return a + b


combine_jit = jax.jit(combine)


def run():
    scores = np.zeros((8,), dtype=np.float32)
    pattern = np.zeros((8,), dtype=jnp.bfloat16)
    return combine_jit(scores, pattern)
