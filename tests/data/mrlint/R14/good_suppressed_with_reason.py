"""R14 negative: the same mixed-ladder boundary, suppressed with a
justified pragma (e.g. the kernel contract pins the upcast itself)."""
import jax
import jax.numpy as jnp
import numpy as np


def combine(a, b):
    return a + b


combine_jit = jax.jit(combine)


def run():
    scores = np.zeros((8,), dtype=np.float32)
    pattern = np.zeros((8,), dtype=jnp.bfloat16)
    # mrlint: disable=R14(fixture: kernel promotes bf16 on read, upcast placement is pinned)
    return combine_jit(scores, pattern)
