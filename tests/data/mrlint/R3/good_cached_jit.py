"""R3 true negatives: the module-cache jit idiom, a static branch on a
config, and a hashable static call."""
import jax


def compute(x, mode):
    return x if mode == "fwd" else -x  # mode is static — fine


compute_jit = jax.jit(compute, static_argnums=(1,))


def call(x):
    return compute_jit(x, "fwd")


_CACHED = None


def cached_jit():
    global _CACHED
    if _CACHED is None:
        _CACHED = jax.jit(compute, static_argnums=(1,))
    return _CACHED
