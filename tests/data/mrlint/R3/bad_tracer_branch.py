"""R3 true positive: Python `if` on a traced value."""
import jax


def relu_ish(x):
    if x > 0:  # concretizes the tracer
        return x
    return -x


relu_jit = jax.jit(relu_ish)
