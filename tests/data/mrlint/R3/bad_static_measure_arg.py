"""R3 true positive (value->static dataflow): a host-measured count in
a static argument position keys the jit cache on the data itself — one
retrace per distinct window."""
import jax


def kernel(buf, n):
    return buf[:n] * 2


kernel_jit = jax.jit(kernel, static_argnums=(1,))


def run_window(spans, buf):
    n = len(spans)
    return kernel_jit(buf, n)
