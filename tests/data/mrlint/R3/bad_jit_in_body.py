"""R3 true positive: jax.jit built per call — a fresh wrapper (and a
fresh compile cache) every invocation."""
import jax


def run(fn, x):
    return jax.jit(fn)(x)  # recompiles every call
