"""R3 true positive: a list literal passed in a static jit position —
static args are cache keys and must be hashable."""
import jax


def apply(x, opts):
    return x


apply_jit = jax.jit(apply, static_argnums=(1,))


def call(x):
    return apply_jit(x, [1, 2, 3])
