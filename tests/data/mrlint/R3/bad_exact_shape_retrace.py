"""R3 true positive (value->shape dataflow, the pad_policy="exact"
hazard): the staging buffer's extent is the raw span count, so every
distinct window keys a fresh trace."""
import jax
import numpy as np


def kernel(buf):
    return buf * 2


kernel_jit = jax.jit(kernel)


def run_window(spans):
    n = len(spans)
    buf = np.zeros(n, np.float32)
    return kernel_jit(buf)
