"""R3 negative: the measured extent is bucketed through a pad helper
before shaping the staging buffer — shapes repeat across windows and
the jit cache converges."""
import jax
import numpy as np


def pad_extent(n, multiple=256):
    return ((n + multiple - 1) // multiple) * multiple


def kernel(buf):
    return buf * 2


kernel_jit = jax.jit(kernel)


def run_window(spans):
    n = pad_extent(len(spans))
    buf = np.zeros(n, np.float32)
    return kernel_jit(buf)
