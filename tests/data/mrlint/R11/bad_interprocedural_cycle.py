"""R11 bad: the lock-order cycle only exists ACROSS functions — each
holder calls a helper that takes the second lock (acquire-via-callee
edges)."""

import threading


class Coordinator:
    def __init__(self):
        self._lease_lock = threading.Lock()
        self._seal_lock = threading.Lock()

    def renew(self):
        with self._lease_lock:
            self._record_seal()

    def _record_seal(self):
        with self._seal_lock:
            pass

    def seal(self):
        with self._seal_lock:
            self._touch_lease()

    def _touch_lease(self):
        with self._lease_lock:
            pass
