"""R11 bad: the textbook AB/BA deadlock — two methods nest the same
two locks in opposite orders."""

import threading


class Pipeline:
    def __init__(self):
        self._stage_lock = threading.Lock()
        self._stats_lock = threading.Lock()

    def advance(self):
        with self._stage_lock:
            with self._stats_lock:
                pass

    def report(self):
        with self._stats_lock:
            with self._stage_lock:
                pass
