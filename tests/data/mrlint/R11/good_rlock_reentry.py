"""R11 good: re-entering a held RLock through a callee is legal —
reentrant locks are exempt from the self-edge."""

import threading


class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def publish(self, item):
        with self._lock:
            self.evict()

    def evict(self):
        with self._lock:
            pass
