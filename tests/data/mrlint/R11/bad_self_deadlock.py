"""R11 bad: re-acquiring a held NON-reentrant lock through a callee —
the thread deadlocks on itself (a plain threading.Lock is not an
RLock)."""

import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()

    def publish(self, item):
        with self._lock:
            self.evict()

    def evict(self):
        with self._lock:
            pass
