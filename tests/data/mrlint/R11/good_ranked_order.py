"""R11 good: both paths acquire the two locks in the SAME global
order (stage before stats) — the acquisition graph stays a DAG."""

import threading


class Pipeline:
    def __init__(self):
        self._stage_lock = threading.Lock()
        self._stats_lock = threading.Lock()

    def advance(self):
        with self._stage_lock:
            with self._stats_lock:
                pass

    def report(self):
        with self._stage_lock:
            with self._stats_lock:
                pass
