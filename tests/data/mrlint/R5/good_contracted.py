"""R5 true negatives: a @contract-annotated entry point, and private /
non-entry-point names that are out of scope."""
from microrank_tpu.analysis.contracts import contract


@contract(graph="windowgraph", returns=("int32[K]", "float32[K]", "int32[]"))
def rank_window_annotated(graph, cfg):
    return graph, cfg


def _rank_window_private(graph):  # private: out of scope
    return graph


def build_graph(graph):  # not a rank/spectrum seam
    return graph
