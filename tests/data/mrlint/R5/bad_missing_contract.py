"""R5 true positive: a public rank entry point with no @contract."""


def rank_window_plain(graph, cfg):
    return graph, cfg
