"""R16 positive: the production path dispatches a statically
enumerable compile key ("packed") the warmup path never declares — the
first real request pays the compile the warmup manifest exists to
eliminate."""
import jax


def rank(x, kernel):
    return x


rank_jit = jax.jit(rank, static_argnames=("kernel",))


def warm_start(x):
    rank_jit(x, kernel="kind")


def serve(x):
    return rank_jit(x, kernel="packed")
