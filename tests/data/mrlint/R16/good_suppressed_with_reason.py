"""R16 negative: the same uncovered key, suppressed with a justified
pragma (e.g. a deliberately cold fallback kernel)."""
import jax


def rank(x, kernel):
    return x


rank_jit = jax.jit(rank, static_argnames=("kernel",))


def warm_start(x):
    rank_jit(x, kernel="kind")


def serve(x):
    # mrlint: disable=R16(fixture: packed is the cold-path fallback, compile on demand is intended)
    return rank_jit(x, kernel="packed")
