"""R2 true negative: f32/bf16 dtypes in a jax module are the contract."""
import jax.numpy as jnp


def cast(x):
    return x.astype(jnp.bfloat16), jnp.float32(0.5)
