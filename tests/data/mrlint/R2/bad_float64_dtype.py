"""R2 true positive: float64 dtypes in a jax-importing module."""
import jax.numpy as jnp
import numpy as np


def widen(x):
    y = jnp.asarray(x, dtype=np.float64)  # upcasts the whole chain
    return y.astype("float64")
