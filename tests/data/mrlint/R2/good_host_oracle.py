"""R2 true negative: a numpy-only float64 oracle (no jax import) — the
sparse_oracle/numpy_ref pattern — is out of R2's scope by design."""
import numpy as np


def oracle(x):
    return np.asarray(x, dtype=np.float64).sum()
