"""R4 true negative: the donated buffer is never read after the call
(reads before it are fine, as is donating the last use)."""
import jax


def bump(x):
    return x + 1


bump_donated = jax.jit(bump, donate_argnums=(0,))


def run(x):
    total = x.sum()  # read BEFORE donation — fine
    y = bump_donated(x)
    return y + total
