"""R4 true positive: a buffer read after being passed in a donated
argument position."""
import jax


def bump(x):
    return x + 1


bump_donated = jax.jit(bump, donate_argnums=(0,))


def run(x):
    y = bump_donated(x)
    return y + x  # x's buffer was handed to XLA — deleted by now
