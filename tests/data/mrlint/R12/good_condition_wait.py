"""R12 good: ``Condition.wait`` on the HELD condition is exempt — wait
releases the lock by contract (the scheduler's idle-park idiom)."""

import threading


class Scheduler:
    def __init__(self):
        self._cond = threading.Condition()
        self.queue = []

    def pop(self, timeout):
        with self._cond:
            if not self.queue:
                self._cond.wait(timeout=timeout)
            if self.queue:
                return self.queue.pop(0)
        return None

    def push(self, item):
        with self._cond:
            self.queue.append(item)
            self._cond.notify()
