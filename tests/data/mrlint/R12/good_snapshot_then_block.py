"""R12 good: the fix shape — snapshot state under the lock, release
it, THEN do the blocking I/O."""

import threading
import urllib.request


class IncidentNotifier:
    def __init__(self, url):
        self._lock = threading.Lock()
        self.url = url
        self.pending = []

    def notify(self):
        with self._lock:
            batch = list(self.pending)
            self.pending = []
        for payload in batch:
            req = urllib.request.Request(self.url, data=payload)
            urllib.request.urlopen(req, timeout=5.0)
