"""R12 bad: the blocking call hides one hop away — the method called
under the lock waits on a pool future (``submit(...).result()``)."""

import threading
from concurrent.futures import ThreadPoolExecutor


class Builder:
    def __init__(self):
        self._lock = threading.Lock()
        self.pool = ThreadPoolExecutor(2)
        self.built = []

    def build_next(self, graph):
        with self._lock:
            out = self._run_build(graph)
            self.built.append(out)

    def _run_build(self, graph):
        return self.pool.submit(len, graph).result()
