"""R12 bad: a backoff sleep inside the lock — every thread contending
on the lock sleeps too."""

import threading
import time


class RateLimiter:
    def __init__(self):
        self._lock = threading.Lock()
        self.last = 0.0

    def pace(self):
        with self._lock:
            time.sleep(0.2)
            self.last = time.monotonic()
