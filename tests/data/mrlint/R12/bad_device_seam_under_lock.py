"""R12 bad: a device dispatch/fetch seam entered while a lock is held
— every contending thread waits out device latency behind a host
lock."""

import threading

from microrank_tpu.rank_backends.blob import stage_rank_window


class Dispatcher:
    def __init__(self, config):
        self._lock = threading.Lock()
        self.config = config

    def rank(self, graph, kernel):
        with self._lock:
            return stage_rank_window(
                graph,
                self.config.pagerank,
                self.config.spectrum,
                kernel,
                False,
            )
