"""R12 bad: the webhook-hang bug class — an HTTP POST issued while the
incident lock is held; a hung endpoint stalls every thread touching
incident state."""

import threading
import urllib.request


class IncidentNotifier:
    def __init__(self, url):
        self._lock = threading.Lock()
        self.url = url
        self.sent = 0

    def notify(self, payload):
        with self._lock:
            req = urllib.request.Request(self.url, data=payload)
            urllib.request.urlopen(req, timeout=5.0)
            self.sent += 1
