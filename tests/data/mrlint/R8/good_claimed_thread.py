"""R8 negative: the engine thread claims device ownership at its root —
it IS the owner; dispatching from here is the program-order rule
working as designed."""
import threading

import jax.numpy as jnp

from microrank_tpu.utils.guards import claim_device_owner


class EngineThread(threading.Thread):
    def run(self):
        claim_device_owner("engine")
        for batch in self.batches:
            out = jnp.sum(batch)
            self.sink.append(out)
