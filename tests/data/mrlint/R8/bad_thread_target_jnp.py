"""R8 true positive: a polling thread target dispatches jax work — the
poller races the owner loop's program order on the shared device."""
import threading

import jax.numpy as jnp


def poll_device(buf):
    return jnp.sum(buf) * 2


def start_poller(buf):
    t = threading.Thread(target=poll_device, name="poller", args=(buf,))
    t.start()
    return t
