"""R8 true positive: a future done-callback fetching results — it runs
on whichever pool worker completed the future, not the owner thread."""
import jax


def build_host_graph(graph):
    return graph


def fetch_result(fut):
    return jax.device_get(fut.result())


def launch(pool, graph):
    fut = pool.submit(build_host_graph, graph)
    fut.add_done_callback(fetch_result)
    return fut
