"""R8 true positive: an HTTP handler ranking inline on the asyncio
event-loop thread instead of enqueueing to the scheduler."""
import jax
import jax.numpy as jnp


def kernel(x):
    return jnp.cumsum(x)


kernel_jit = jax.jit(kernel)


async def handle_rank(request, buf):
    out = kernel_jit(buf)
    return out
