"""R8 true positive: an incident webhook sink fetching device arrays —
sink callbacks run inside the dispatch lifecycle (and may retry from
helper threads); they must stay host-only."""
import json

import jax


class WebhookSink:
    def emit(self, incident, scores):
        payload = {"scores": jax.device_get(scores).tolist()}
        return json.dumps(payload)
