"""R8 true positive: window staging submitted to an unauthorized worker
pool — the device seam runs on whatever worker picks it up."""
from concurrent.futures import ThreadPoolExecutor


def launch_async(graph, cfg):
    pool = ThreadPoolExecutor(2, "staging")
    return pool.submit(stage_graph, graph, cfg)


def stage_graph(graph, cfg):
    return stage_rank_window(
        graph, cfg.pagerank, cfg.spectrum, "coo", cfg.runtime.blob_staging
    )
