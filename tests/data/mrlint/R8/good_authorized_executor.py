"""R8 negative: staging delegated to an executor whose workers register
as sanctioned delegates via initializer=authorize_device_thread (the
table lane's async staging/fetch pattern — single-width ordered RPCs)."""
from concurrent.futures import ThreadPoolExecutor

from microrank_tpu.utils.guards import authorize_device_thread


def launch_async(graph, cfg):
    pool = ThreadPoolExecutor(
        1, "mr-stage", initializer=authorize_device_thread
    )
    return pool.submit(stage_graph, graph, cfg)


def stage_graph(graph, cfg):
    return stage_rank_window(
        graph, cfg.pagerank, cfg.spectrum, "coo", cfg.runtime.blob_staging
    )
