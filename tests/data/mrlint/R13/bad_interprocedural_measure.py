"""R13 positive: a live measurement reaches a static jit argument
through a helper call — invisible to R3's local pattern, caught by the
interprocedural provenance analysis (⊤ flows through n_rows)."""
import jax


def n_rows(table):
    return len(table)


def rank(x, n):
    return x * n


rank_jit = jax.jit(rank, static_argnums=(1,))


def serve(table, x):
    count = n_rows(table)
    return rank_jit(x, count)
