"""R13 negative: same flow, suppressed in place with a justified
pragma (the escape hatch leaves an audit trail; bare disables are R0)."""
import jax


def n_rows(table):
    return len(table)


def rank(x, n):
    return x * n


rank_jit = jax.jit(rank, static_argnums=(1,))


def serve(table, x):
    count = n_rows(table)
    # mrlint: disable=R13(fixture: table rows bounded by the admission cap upstream)
    return rank_jit(x, count)
