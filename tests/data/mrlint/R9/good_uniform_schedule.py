"""R9 negative: unconditional per-iteration psum — every shard issues
the identical collective sequence; data dependence is expressed by
masking the operand, not by branching around the collective."""
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map


def kernel(x):
    mask = x > 0
    contrib = jnp.where(mask, x, 0.0)
    total = jax.lax.psum(contrib, "shards")
    return x / (total + 1e-9)


def rank(mesh, spec, x):
    return shard_map(kernel, mesh=mesh, in_specs=spec, out_specs=spec)(x)
