"""R9 negative: the branch predicate is a trace-static config flag —
every shard traces the same path, so the schedule stays uniform even
though the two paths differ."""
import jax
from jax.experimental.shard_map import shard_map

USE_COMPENSATED = True


def kernel(x):
    if USE_COMPENSATED:
        hi = jax.lax.psum(x, "shards")
        lo = jax.lax.psum(x - hi, "shards")
        return hi + lo
    return jax.lax.psum(x, "shards")


def rank(mesh, spec, x):
    return shard_map(kernel, mesh=mesh, in_specs=spec, out_specs=spec)(x)
