"""R9 true positive: a psum issued only when this shard's local sum is
positive — shards whose operands branch differently fall out of the
collective schedule (deadlock on a real mesh)."""
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map


def kernel(x):
    if jnp.sum(x) > 0:
        x = jax.lax.psum(x, "shards")
    return x


def rank(mesh, specs, x):
    return shard_map(kernel, mesh=mesh, in_specs=specs, out_specs=specs)(x)
