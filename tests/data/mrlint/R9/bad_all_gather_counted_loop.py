"""R9 true positive: all_gather inside a loop iterating a shard-local
operand — shards with different extents run different numbers of
collectives."""
import jax
from jax.experimental.shard_map import shard_map


def widen(x, steps):
    for _ in steps:
        x = jax.lax.all_gather(x, "shards").sum(axis=0)
    return x


def rank(mesh, spec, x, steps):
    return shard_map(widen, mesh=mesh, in_specs=spec, out_specs=spec)(
        x, steps
    )
