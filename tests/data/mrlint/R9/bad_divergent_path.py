"""R9 true positive: two call paths into the same collective-issuing
kernel, one of them under a data-dependent branch — the branching shard
issues psum twice while the rest issue it once."""
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map


def combine(x):
    return jax.lax.psum(x, "shards")


def kernel(x, y):
    out = combine(x)
    if jnp.max(y) > 0:
        out = out + combine(y)
    return out


def rank(mesh, spec, x, y):
    return shard_map(kernel, mesh=mesh, in_specs=spec, out_specs=spec)(x, y)
