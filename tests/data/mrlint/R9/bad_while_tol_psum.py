"""R9 true positive: a convergence while-loop on a traced residual with
the cross-shard combine inside — per-shard iteration counts diverge and
so do the collective sequences."""
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map


def iterate(x):
    r = jnp.max(jnp.abs(x))
    while r > 1e-3:
        x = jax.lax.psum(x, "shards") * 0.5
        r = jnp.max(jnp.abs(x))
    return x


def rank(mesh, spec, x):
    return shard_map(iterate, mesh=mesh, in_specs=spec, out_specs=spec)(x)
