"""Render the adversarial corpus fixtures (one CSV per corruption
class + a mixed file) from the seeded synthetic generator + the
seeded corruption functions (ingest.hostile) — run from the repo root:

    python tests/data/hostile/make_fixtures.py

The CSVs are checked in; this script exists so the fixtures are
regenerable (and auditable) rather than hand-typed. Each file is a
small abnormal window (one injected latency fault, truth in
TRUTH.json) with exactly one corruption class applied; ``mixed.csv``
stacks all five classes. ``clean.csv``/``normal.csv`` are the
uncorrupted pair the admission idempotence property and the lane
tests baseline against.
"""

from __future__ import annotations

import json
from pathlib import Path

HERE = Path(__file__).parent
SEED = 20250804
FRACTION = 0.08
BOMB_OPS = 48


def main() -> None:
    from microrank_tpu.ingest.hostile import (
        CORRUPTION_KINDS,
        corrupt_frame,
        corrupt_timeline,
    )
    from microrank_tpu.testing import SyntheticConfig, generate_case

    case = generate_case(
        SyntheticConfig(n_operations=16, n_traces=60, seed=11)
    )
    case.normal.to_csv(HERE / "normal.csv", index=False)
    case.abnormal.to_csv(HERE / "clean.csv", index=False)
    for kind in CORRUPTION_KINDS:
        corrupt_frame(
            case.abnormal, kind, seed=SEED, fraction=FRACTION,
            bomb_ops=BOMB_OPS,
        ).to_csv(HERE / f"{kind}.csv", index=False)
    corrupt_timeline(
        case.abnormal, CORRUPTION_KINDS, seed=SEED,
        fraction=FRACTION, bomb_ops=BOMB_OPS,
    ).to_csv(HERE / "mixed.csv", index=False)
    (HERE / "TRUTH.json").write_text(
        json.dumps(
            {
                "fault_pod_op": case.fault_pod_op,
                "fault_service_op": case.fault_service_op,
                "seed": SEED,
                "fraction": FRACTION,
                "bomb_ops": BOMB_OPS,
            },
            indent=2,
        )
        + "\n"
    )
    print(f"fixtures written under {HERE}")


if __name__ == "__main__":
    main()
