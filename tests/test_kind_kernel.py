"""Kind-compressed reduced-precision kernel (ISSUE 14).

Covers the tentpole end to end: the compression math (folded
multiplicity weights reproduce uncollapsed scores bit-for-bit in f64),
the scaled-int8 operand quantization's edge cases, degenerate builds,
the blob round-trip of the new fields, aux/kernel auto-select policy,
tie-aware oracle parity for every precision on collapsed AND
uncollapsed builds, single-device AND sharded, the scenario-matrix
family parity gate vs the packed kernel, and the warm-start seam
(iteration counts drop on an overlapping-window replay, residual-trace
proven).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from conftest import partition_case
from microrank_tpu.config import MicroRankConfig, PageRankConfig
from microrank_tpu.graph import build_window_graph
from microrank_tpu.graph.build import (
    DEFAULT_KIND_DEDUP_THRESHOLD,
    kind_aux,
    kind_dedup_ratio,
    resolve_aux,
)
from microrank_tpu.rank_backends.jax_tpu import (
    choose_kernel,
    device_subset,
    quantize_i8,
    rank_window_device,
    rank_window_warm_device,
)
from microrank_tpu.rank_backends.sparse_oracle import rank_window_sparse
from microrank_tpu.testing import SyntheticConfig, generate_case
from microrank_tpu.utils.ranking_compare import tie_aware_topk_agreement

CFG = MicroRankConfig()


def _span_frame(traces):
    """Tiny span frame from [(traceID, [op names])]: one pod-op per
    span, parent chain within each trace."""
    rows = []
    t0 = pd.Timestamp("2025-03-01 10:00:00")
    for tid, ops in traces:
        for i, op in enumerate(ops):
            rows.append(
                {
                    "traceID": tid,
                    "spanID": f"{tid}-s{i}",
                    "ParentSpanId": f"{tid}-s{i - 1}" if i else "",
                    "serviceName": op.split("_")[0],
                    "podName": op.split("_")[0] + "-0",
                    "operationName": op.split("_")[1],
                    "startTime": t0,
                    "endTime": t0 + pd.Timedelta(milliseconds=5),
                    "duration": 5000,
                }
            )
    return pd.DataFrame(rows)


@pytest.fixture(scope="module")
def kind_case():
    """A window with real kind structure: two identical abnormal traces
    (one kind of multiplicity 2, len 3 so 1/len is inexact in binary)
    plus distinct singleton kinds."""
    frame = _span_frame(
        [
            ("a1", ["svcA_op1", "svcB_op2", "svcC_op3"]),
            ("a2", ["svcA_op1", "svcB_op2", "svcC_op3"]),
            ("a3", ["svcA_op1", "svcD_op4"]),
            ("n1", ["svcA_op1", "svcB_op2"]),
            ("n2", ["svcA_op1", "svcC_op3", "svcD_op4"]),
        ]
    )
    nrm = ["n1", "n2"]
    abn = ["a1", "a2", "a3"]
    return frame, nrm, abn


def _f64_partition_scores(g, anomaly, iters=25, d=0.85, alpha=0.01):
    """Float64 reference iteration straight off the (possibly
    collapsed) COO arrays, multiplicity-weighted exactly as the device
    kernels read them — the hand-checkable twin of the folded math."""
    v = g.cov_unique.shape[0]
    t = g.kind.shape[0]
    n_cols = int(g.n_cols)
    n_live = int(g.n_traces) if n_cols < 0 else n_cols
    p_sr = np.zeros((v, t))
    p_rs = np.zeros((t, v))
    n_inc = int(g.n_inc)
    for e in range(n_inc):
        p_sr[g.inc_op[e], g.inc_trace[e]] += np.float64(g.sr_val[e])
        p_rs[g.inc_trace[e], g.inc_op[e]] += np.float64(g.rs_val[e])
    p_ss = np.zeros((v, v))
    for e in range(int(g.n_ss)):
        p_ss[g.ss_child[e], g.ss_parent[e]] += np.float64(g.ss_val[e])
    kind = np.asarray(g.kind, np.float64)
    mult = np.ones(t) if n_cols < 0 else kind
    live = np.arange(t) < n_live
    inv_kind = np.where(live, 1.0 / np.maximum(kind, 1), 0.0)
    kind_sum = float((mult * inv_kind).sum())
    if not anomaly:
        pref = np.where(live, inv_kind / kind_sum, 0.0)
    else:
        tlen = np.asarray(g.tracelen, np.float64)
        inv_len = np.where(live, 1.0 / np.maximum(tlen, 1), 0.0)
        num_sum = float((mult * inv_len).sum())
        pref = np.where(
            live, 0.5 / num_sum / (kind / kind_sum * 0.5 + inv_len), 0.0
        )
    n_total = float(int(g.n_ops) + int(g.n_traces))
    sv = np.where(np.asarray(g.op_present), 1.0 / n_total, 0.0)
    rv = np.where(live, 1.0 / n_total, 0.0)
    for _ in range(iters):
        sv_new = d * (p_sr @ rv + alpha * (p_ss @ sv))
        rv_new = d * (p_rs @ sv) + (1 - d) * pref
        sv = sv_new / sv_new.max()
        rv = rv_new / rv_new.max()
    return sv


# ------------------------------------------------------ compression math


def test_folded_multiplicity_reproduces_uncollapsed_f64(kind_case):
    """The core equivalence claim, bit-for-bit in f64: PageRank over
    weighted unique kinds (sr_val = m/len folded, preference sums
    multiplicity-weighted) equals PageRank over per-trace columns.
    Multiplicity 2 is a power of two, so even the f32-stored folded
    values are exactly 2x the per-trace values and the f64 iterations
    agree to the last bit."""
    frame, nrm, abn = kind_case
    g_u, names, _, _ = build_window_graph(
        frame, nrm, abn, aux="none", collapse="off"
    )
    g_c, names_c, _, _ = build_window_graph(
        frame, nrm, abn, aux="kind", collapse="on"
    )
    assert names == names_c
    assert int(g_c.abnormal.n_cols) == 2   # {op1,op2,op3} x2 + {op1,op4}
    assert int(g_c.abnormal.n_traces) == 3
    # The folded forward value IS m/len: column 0 stands for two
    # traces of three spans each.
    mult_col = np.asarray(g_c.abnormal.kind)[: int(g_c.abnormal.n_cols)]
    assert sorted(mult_col.tolist()) == [1, 2]
    for side in ("normal", "abnormal"):
        anomaly = side == "abnormal"
        sv_u = _f64_partition_scores(getattr(g_u, side), anomaly)
        sv_c = _f64_partition_scores(getattr(g_c, side), anomaly)
        assert np.array_equal(sv_u, sv_c), side


def test_kind_aux_views(kind_case):
    """kind_aux derives the int8 pattern + ss row offsets exactly from
    the bitmap/edge list."""
    frame, nrm, abn = kind_case
    g, _, _, _ = build_window_graph(
        frame, nrm, abn, aux="kind", collapse="on"
    )
    for part in (g.normal, g.abnormal):
        t_pad = part.kind.shape[0]
        v_pad = part.cov_unique.shape[0]
        assert part.cov_i8.shape == (v_pad, t_pad)
        assert part.cov_i8.dtype == np.int8
        assert set(np.unique(part.cov_i8)) <= {0, 1}
        # Pattern matches the bitmap bit-for-bit.
        bits = np.unpackbits(part.cov_bits, axis=1)[:, :t_pad]
        assert np.array_equal(part.cov_i8, bits.astype(np.int8))
        # Row offsets bracket exactly the ss edges of each child.
        assert part.ss_indptr.shape == (v_pad + 1,)
        n_ss = int(part.n_ss)
        counts = np.bincount(
            np.asarray(part.ss_child[:n_ss]), minlength=v_pad
        )
        assert np.array_equal(np.diff(part.ss_indptr), counts)


# --------------------------------------------------------- int8 quantize


def test_quantize_i8_edges():
    # All-zero vector: guarded scale, all-zero q.
    q, s = quantize_i8(jnp.zeros(8))
    assert float(s) == 1.0 and int(jnp.abs(q).max()) == 0
    # Max magnitude lands exactly on +/-127; nothing wraps.
    x = jnp.asarray([-3.0, -1.5, 0.0, 1e-9, 3.0])
    q, s = quantize_i8(x)
    assert q.dtype == jnp.int8
    assert int(q[0]) == -127 and int(q[-1]) == 127
    # Round-trip error bounded by scale/2 everywhere.
    err = np.abs(np.asarray(q, np.float64) * float(s) - np.asarray(x))
    assert (err <= float(s) / 2 + 1e-12).all()
    # Huge dynamic range: tiny entries quantize to 0 (no wraparound,
    # no negative surprise), the max stays exact.
    x = jnp.asarray([1e-30, 1e30])
    q, s = quantize_i8(x)
    assert int(q[0]) == 0 and int(q[1]) == 127
    assert np.isfinite(float(s))


# ------------------------------------------------------ degenerate builds


def test_single_kind_window_builds_and_ranks():
    """Every abnormal trace identical -> ONE kind column; the kernel
    still ranks and matches the packed kernel."""
    frame = _span_frame(
        [
            ("a1", ["svcA_op1", "svcB_op2"]),
            ("a2", ["svcA_op1", "svcB_op2"]),
            ("a3", ["svcA_op1", "svcB_op2"]),
            ("n1", ["svcA_op1"]),
        ]
    )
    g, names, _, _ = build_window_graph(
        frame, ["n1"], ["a1", "a2", "a3"], aux="kind", collapse="on"
    )
    assert int(g.abnormal.n_cols) == 1
    out_k = rank_window_device(
        device_subset(g, "kind"), CFG.pagerank, CFG.spectrum, None, "kind"
    )
    g2, _, _, _ = build_window_graph(
        frame, ["n1"], ["a1", "a2", "a3"], aux="packed", collapse="on"
    )
    out_p = rank_window_device(
        device_subset(g2, "packed"), CFG.pagerank, CFG.spectrum, None,
        "packed",
    )
    n = int(out_k[2])
    assert n == int(out_p[2]) > 0
    assert np.array_equal(
        np.asarray(out_k[0])[:n], np.asarray(out_p[0])[:n]
    )


def test_empty_partition_kind_build():
    """A partition with no call edges / minimal traces still produces
    well-formed kind views (all-zero offsets, zero pattern rows)."""
    frame = _span_frame([("a1", ["svcA_op1"]), ("n1", ["svcB_op2"])])
    g, _, _, _ = build_window_graph(
        frame, ["n1"], ["a1"], aux="kind", collapse="on"
    )
    for part in (g.normal, g.abnormal):
        assert part.cov_i8.shape[-1] == part.kind.shape[0]
        assert int(part.n_ss) == 0
        assert np.array_equal(
            part.ss_indptr, np.zeros_like(part.ss_indptr)
        )
    # And it ranks without NaNs.
    ti, ts, nv = rank_window_device(
        device_subset(g, "kind"), CFG.pagerank, CFG.spectrum, None, "kind"
    )
    assert np.isfinite(np.asarray(ts)[: int(nv)]).all()


# ------------------------------------------------------- blob round trip


def test_blob_roundtrip_kind_fields(kind_case):
    from microrank_tpu.rank_backends.blob import (
        pack_graph_blob,
        unpack_graph_blob,
    )

    frame, nrm, abn = kind_case
    g, _, _, _ = build_window_graph(
        frame, nrm, abn, aux="kind", collapse="on"
    )
    sub = device_subset(g, "kind")
    blob, layout = pack_graph_blob(sub)
    out = jax.jit(
        lambda b: unpack_graph_blob(b, layout)
    )(jnp.asarray(blob))
    for side in ("normal", "abnormal"):
        a, b = getattr(sub, side), getattr(out, side)
        assert np.array_equal(np.asarray(b.cov_i8), a.cov_i8)
        assert np.asarray(b.cov_i8).dtype == np.int8
        assert np.array_equal(np.asarray(b.ss_indptr), a.ss_indptr)
        assert np.array_equal(np.asarray(b.inv_tracelen), a.inv_tracelen)


# ----------------------------------------------------- auto-select policy


def test_resolve_aux_kind_threshold():
    # No measured dedup -> packed as before.
    assert resolve_aux("auto", 64, (64, 64)) == "packed"
    # Past the threshold -> kind; below -> packed.
    assert resolve_aux("auto", 64, (8, 8), dedup=8.0) == "kind"
    assert resolve_aux("auto", 64, (8, 8), dedup=1.5) == "packed"
    assert (
        resolve_aux(
            "auto", 64, (8, 8), dedup=2.0, kind_dedup_threshold=2.0
        )
        == "kind"
    )
    # auto_all (the sharded build) never resolves to kind.
    assert resolve_aux("auto_all", 64, (8, 8), dedup=8.0) == "all"
    # Past the bitmap budget the memory-bounded fallback still wins.
    assert (
        resolve_aux(
            "auto", 1 << 16, (1 << 16,), 1 << 20, dedup=100.0
        )
        == "pcsr"
    )
    assert DEFAULT_KIND_DEDUP_THRESHOLD == 4.0


def test_choose_kernel_and_subset(kind_case):
    frame, nrm, abn = kind_case
    g, _, _, _ = build_window_graph(
        frame, nrm, abn, aux="kind", collapse="on"
    )
    assert choose_kernel(g) == "kind"
    assert kind_dedup_ratio(g) > 1.0
    sub = device_subset(g, "kind")
    for part in (sub.normal, sub.abnormal):
        assert part.cov_i8.shape[-1] > 0
        assert part.ss_indptr.shape[-1] > 0
        assert part.cov_bits.shape[-1] == 0
        assert part.ss_bits.shape[-1] == 0
        assert part.inc_op.shape[-1] == 0
        assert part.pc_trace.shape[-1] == 0


def test_auto_pipeline_selects_kind_past_threshold(kind_case):
    """End to end through the backend: collapse auto + measured dedup
    over the threshold -> the auto kernel is kind (and parity holds)."""
    from microrank_tpu.rank_backends.jax_tpu import prepare_window_graph

    frame, nrm, abn = kind_case
    cfg = CFG.replace(
        runtime=dataclasses.replace(
            CFG.runtime, kind_dedup_threshold=1.2, collapse_kinds="on"
        )
    )
    graph, names, kernel = prepare_window_graph(frame, nrm, abn, cfg)
    assert kernel == "kind"
    assert graph.normal.cov_i8.shape[-1] > 0
    # Below threshold: packed keeps the window.
    cfg2 = CFG.replace(
        runtime=dataclasses.replace(
            CFG.runtime, kind_dedup_threshold=1e9, collapse_kinds="on"
        )
    )
    _, _, kernel2 = prepare_window_graph(frame, nrm, abn, cfg2)
    assert kernel2 in ("packed", "packed_bf16")


# ------------------------------------------------------------ rank parity


@pytest.fixture(scope="module")
def synth_case():
    case = generate_case(
        SyntheticConfig(n_operations=30, n_kinds=6, n_traces=200, seed=3)
    )
    nrm, abn = partition_case(case)
    return case, nrm, abn


@pytest.mark.parametrize("precision", ["f32", "bf16", "int8"])
@pytest.mark.parametrize("collapse", ["on", "off"])
def test_kind_parity_vs_f64_oracle(synth_case, precision, collapse):
    """Tie-aware top-5 parity vs the f64 sparse oracle (always ranked
    on an UNCOLLAPSED build) for every precision, collapsed and
    uncollapsed — the acceptance gate's single-device half."""
    case, nrm, abn = synth_case
    g_o, names, _, _ = build_window_graph(
        case.abnormal, nrm, abn, aux="none", collapse="off"
    )
    top_o, sc_o = rank_window_sparse(g_o, names, CFG.pagerank, CFG.spectrum)
    g, names_k, _, _ = build_window_graph(
        case.abnormal, nrm, abn, aux="kind", collapse=collapse
    )
    pr = dataclasses.replace(CFG.pagerank, kind_precision=precision)
    ti, ts, nv = rank_window_device(
        device_subset(g, "kind"), pr, CFG.spectrum, None, "kind"
    )
    n = int(nv)
    ok, why = tie_aware_topk_agreement(
        [names_k[int(i)] for i in np.asarray(ti)[:n]],
        [float(s) for s in np.asarray(ts)[:n]],
        top_o,
        sc_o,
        k=5,
        rtol=5e-2 if precision == "int8" else 1e-3,
        exempt_last=True,
    )
    assert ok, why


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)
def test_kind_parity_sharded(synth_case):
    """The acceptance gate's sharded half: the kind kernel over the
    (windows, shard) mesh reproduces its own single-device ranking and
    the f64 oracle's top-5."""
    from microrank_tpu.parallel import make_mesh, rank_windows_sharded
    from microrank_tpu.parallel.sharded_rank import stage_sharded

    case, nrm, abn = synth_case
    g_o, names, _, _ = build_window_graph(
        case.abnormal, nrm, abn, aux="none", collapse="off"
    )
    top_o, sc_o = rank_window_sparse(g_o, names, CFG.pagerank, CFG.spectrum)
    g, _, _, _ = build_window_graph(
        case.abnormal, nrm, abn, aux="kind", collapse="on"
    )
    mesh = make_mesh((2, 4))
    batched = stage_sharded([g, g], mesh, "kind")
    sti, sts, snv = rank_windows_sharded(
        batched, CFG.pagerank, CFG.spectrum, mesh, "kind"
    )
    ti, ts, nv = rank_window_device(
        device_subset(g, "kind"), CFG.pagerank, CFG.spectrum, None, "kind"
    )
    n = int(nv)
    for b in range(2):
        assert np.array_equal(
            np.asarray(sti)[b][:n], np.asarray(ti)[:n]
        )
    ok, why = tie_aware_topk_agreement(
        [names[int(i)] for i in np.asarray(sti)[0][:n]],
        [float(s) for s in np.asarray(sts)[0][:n]],
        top_o,
        sc_o,
        k=5,
        rtol=1e-3,
        exempt_last=True,
    )
    assert ok, why


# ------------------------------------------- scenario-family parity gate


@pytest.mark.parametrize("family", ["cascade", "multi"])
def test_scenario_family_kind_matches_packed(family):
    """ROADMAP item 5's REMAINING thread: the matrix's harder families
    are the parity gate for the new kernel — kernel='kind' must match
    the packed kernel's tie-aware rankings family-by-family."""
    from microrank_tpu.detect import compute_slo, detect_partition
    from microrank_tpu.rank_backends.jax_tpu import JaxBackend
    from microrank_tpu.scenarios import ScenarioSpec, generate_scenario

    spec = ScenarioSpec(
        name=f"gate-{family}",
        family=family,
        seed=7,
        n_windows=4,
        faulted=(2,),
        n_operations=20,
        n_traces=150,
        n_kinds=12,
    )
    wl = generate_scenario(spec)
    vocab, slo = compute_slo(wl.normal)
    compared = 0
    for i in range(spec.n_windows):
        frame = wl.window_frame(i)
        if len(frame) == 0 or not wl.window_faulted[i]:
            continue
        flag, nrm, abn = detect_partition(CFG, vocab, slo, frame)
        if not (flag and nrm and abn):
            continue
        rankings = {}
        for kernel in ("kind", "packed"):
            cfg = CFG.replace(
                runtime=dataclasses.replace(
                    CFG.runtime, kernel=kernel, collapse_kinds="auto"
                )
            )
            rankings[kernel] = JaxBackend(cfg).rank_window(
                frame, nrm, abn
            )
        ok, why = tie_aware_topk_agreement(
            rankings["kind"][0],
            rankings["kind"][1],
            rankings["packed"][0],
            rankings["packed"][1],
            k=min(5, len(rankings["packed"][0])),
            rtol=1e-3,
            exempt_last=True,
        )
        assert ok, f"{family} window {i}: {why}"
        compared += 1
    assert compared >= 1, f"{family}: no faulted window ranked"


# ---------------------------------------------------------- warm start


def _detect_frame(frame, vocab, slo):
    from microrank_tpu.detect import detect_partition

    flag, nrm, abn = detect_partition(CFG, vocab, slo, frame)
    assert flag and nrm and abn
    return nrm, abn


def _build_retained(frame, nrm, abn):
    from microrank_tpu.explain.bundle import ExplainContext

    graph, names, ids_n, ids_a, cmap = build_window_graph(
        frame, nrm, abn, aux="kind", collapse="on", retain_columns=True
    )
    ectx = ExplainContext.from_build(graph, ids_n, ids_a, *cmap)
    return graph, names, ectx


def test_warm_start_drops_iterations_on_overlapping_replay():
    """The warm-start seam's proof: rank window W cold (tol set),
    capture the converged state, re-rank the OVERLAPPING next window
    warm — the residual-traced iteration count drops, and a fully
    identical window converges almost immediately. Rankings stay
    tie-aware-identical to the cold solve."""
    from microrank_tpu.detect import compute_slo
    from microrank_tpu.rank_backends.warm import (
        capture_warm_state,
        map_warm_state,
    )
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(
            n_operations=24, n_traces=160, n_kinds=12, seed=9
        ),
        3,
        [0, 1, 2],
    )
    frames = tl.timeline
    vocab, slo = compute_slo(tl.normal)
    w_us = int(tl.window_minutes * 60e6)
    start = int(tl.start.value // 1000)
    t_us = frames["startTime"].astype("int64") // 1000

    def window(lo_w, hi_w):
        lo, hi = start + lo_w * w_us, start + hi_w * w_us
        return frames[(t_us >= lo) & (t_us < hi)]

    # W1 = windows [0, 2), W2 = windows [1, 3): 50% span overlap.
    f1, f2 = window(0, 2), window(1, 3)
    nrm1, abn1 = _detect_frame(f1, vocab, slo)
    nrm2, abn2 = _detect_frame(f2, vocab, slo)
    g1, names1, ectx1 = _build_retained(f1, nrm1, abn1)
    g2, names2, ectx2 = _build_retained(f2, nrm2, abn2)
    pr = dataclasses.replace(CFG.pagerank, tol=1e-4, iterations=50)

    def run(graph, init):
        out = jax.device_get(
            rank_window_warm_device(
                device_subset(graph, "kind"), init, pr, CFG.spectrum,
                "kind",
            )
        )
        return out

    cold1 = run(g1, None)
    state = capture_warm_state(names1, ectx1, cold1[5:9])
    cold2 = run(g2, None)
    warm2 = run(g2, map_warm_state(state, names2, ectx2, g2))
    it_cold, it_warm = int(cold2[4]), int(warm2[4])
    assert it_warm <= it_cold
    # Identical-window replay: starting AT the fixed point converges
    # almost immediately — the strict drop.
    state2 = capture_warm_state(names2, ectx2, warm2[5:9])
    again = run(g2, map_warm_state(state2, names2, ectx2, g2))
    assert int(again[4]) <= 3 < it_cold
    # Ranking parity warm vs cold.
    n = int(cold2[2])
    ok, why = tie_aware_topk_agreement(
        [names2[int(i)] for i in np.asarray(warm2[0])[: int(warm2[2])]],
        [float(s) for s in np.asarray(warm2[1])[: int(warm2[2])]],
        [names2[int(i)] for i in np.asarray(cold2[0])[:n]],
        [float(s) for s in np.asarray(cold2[1])[:n]],
        k=min(5, n),
        rtol=1e-3,
        exempt_last=True,
    )
    assert ok, why


def test_stream_engine_threads_warm_state(tmp_path):
    """Engine-level warm-start smoke: consecutive abnormal windows of
    one open incident dispatch through the warm program — the first
    cold (route 'warm_cold'), later ones seeded (route 'warm') — and
    rankings match the warm-off engine tie-aware."""
    from microrank_tpu.config import StreamConfig
    from microrank_tpu.stream import StreamEngine, SyntheticSource

    def source():
        return SyntheticSource(
            n_windows=6,
            faulted=[2, 3, 4],
            synth_config=SyntheticConfig(
                n_operations=24, n_traces=200, n_kinds=16, seed=5
            ),
            pace_seconds=0.01,
            sleep=lambda s: None,
        )

    def run(warm: bool, out):
        cfg = MicroRankConfig(
            stream=StreamConfig(allowed_lateness_seconds=5.0)
        ).replace()
        cfg = cfg.replace(
            runtime=dataclasses.replace(cfg.runtime, warm_start=warm),
            pagerank=PageRankConfig(tol=1e-4, iterations=50),
        )
        eng = StreamEngine(cfg, source(), out_dir=out)
        s = eng.run()
        return [r for r in s.results if r.ranking]

    warm_res = run(True, tmp_path / "warm")
    cold_res = run(False, tmp_path / "cold")
    assert len(warm_res) == len(cold_res) == 3
    assert warm_res[0].route == "warm_cold"
    assert {r.route for r in warm_res[1:]} == {"warm"}
    assert all(r.kind_dedup and r.kind_dedup >= 1.0 for r in warm_res)
    for w, c in zip(warm_res, cold_res):
        assert w.rank_iterations is not None
        ok, why = tie_aware_topk_agreement(
            [n for n, _ in w.ranking],
            [s for _, s in w.ranking],
            [n for n, _ in c.ranking],
            [s for _, s in c.ranking],
            k=min(5, len(c.ranking)),
            rtol=1e-3,
            exempt_last=True,
        )
        assert ok, why
