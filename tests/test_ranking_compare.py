"""The shared tie-aware ranked-list comparator (utils.ranking_compare)
behind both the bench's full-window oracle gate and the multichip
dryrun's sharded-vs-single gate."""

from microrank_tpu.utils.ranking_compare import tie_aware_topk_agreement


def _ok(*a, **kw):
    agree, why = tie_aware_topk_agreement(*a, **kw)
    return agree


def test_identical_lists_agree():
    assert _ok(["x", "y"], [1.0, 0.5], ["x", "y"], [1.0, 0.5], 2)


def test_true_tie_permutation_agrees():
    assert _ok(["x", "y"], [1.0, 1.0], ["y", "x"], [1.0, 1.0], 2)


def test_swapped_non_tied_rankings_fail():
    assert not _ok(["x", "y"], [1.0, 0.5], ["y", "x"], [1.0, 0.5], 2)


def test_different_id_fails():
    assert not _ok(["x", "y"], [1.0, 0.5], ["x", "z"], [1.0, 0.5], 2)


def test_score_mismatch_fails():
    assert not _ok(["x", "y"], [1.0, 0.5], ["x", "y"], [1.0, 0.4], 2)


def test_length_mismatch_within_k_fails():
    assert not _ok(["x", "y"], [1.0, 0.5], ["x"], [1.0], 2)


def test_truncation_boundary_swap_needs_exemption():
    # Last kept rank holds different near-tied ids (the other fell past
    # the cut): fails strictly, passes with exempt_last.
    a = (["x", "y"], [1.0, 0.5])
    b = (["x", "z"], [1.0, 0.5])
    assert not _ok(*a, *b, 2)
    assert _ok(*a, *b, 2, exempt_last=True)


def test_exemption_does_not_cover_inner_ranks():
    a = (["x", "q", "y"], [1.0, 0.7, 0.5])
    b = (["x", "r", "y"], [1.0, 0.7, 0.5])
    assert not _ok(*a, *b, 3, exempt_last=True)


def test_k_truncates_longer_lists():
    assert _ok(
        ["x", "y", "a"], [1.0, 0.5, 0.1], ["x", "y", "b"], [1.0, 0.5, 0.2], 2
    )
