"""Sharded/batched ranking on the 8-device virtual CPU mesh
(SURVEY.md §4 item 4: same pjit/shard_map code paths as a real slice)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import partition_case
from microrank_tpu.config import MicroRankConfig
from microrank_tpu.graph import build_window_graph
from microrank_tpu.parallel import (
    make_mesh,
    rank_windows_batched,
    rank_windows_sharded,
    single_axis_mesh,
    stack_window_graphs,
)
from microrank_tpu.rank_backends.jax_tpu import rank_window_device
from microrank_tpu.testing import SyntheticConfig, generate_case

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)


@pytest.fixture(scope="module")
def window_batch():
    graphs, namelists = [], []
    for seed in (1, 2, 3, 4):
        case = generate_case(
            SyntheticConfig(n_operations=20, n_traces=100, seed=seed)
        )
        nrm, abn = partition_case(case)
        graph, names, _, _ = build_window_graph(case.abnormal, nrm, abn)
        graphs.append(graph)
        namelists.append(names)
    return graphs, namelists


def _assert_rank_equal_tieaware(ti, ts, si, ss, rtol=1e-5):
    """Positional rank equality, except where the two paths' summation
    trees differ (coo segment sums vs sharded csr prefix sums): a
    positional mismatch is allowed only between near-equal scores —
    EXACT ties are pinned by the op-index tie key, but ~1-ulp near-ties
    legitimately flip across kernels."""
    ti, ts = np.asarray(ti), np.asarray(ts)
    si, ss = np.asarray(si), np.asarray(ss)
    assert set(ti.tolist()) == set(si.tolist())
    for p in range(len(ti)):
        if ti[p] != si[p]:
            a, b = float(ts[p]), float(ss[p])
            assert np.isfinite(a) and np.isfinite(b), (p, a, b)
            assert abs(a - b) <= rtol * max(abs(a), abs(b), 1e-12), (p, a, b)


def test_sharded_matches_single_device(window_batch):
    graphs, namelists = window_batch
    cfg = MicroRankConfig()
    mesh = make_mesh((2, 4))
    stacked = stack_window_graphs(graphs, shard_multiple=4)
    sti, sts, stv = rank_windows_sharded(
        jax.tree.map(jnp.asarray, stacked), cfg.pagerank, cfg.spectrum, mesh
    )
    for i, g in enumerate(graphs):
        ti, ts, tv = rank_window_device(
            jax.tree.map(jnp.asarray, g), cfg.pagerank, cfg.spectrum
        )
        # Same top-1 op by name; same candidate ordering up to near-ties.
        assert namelists[i][int(ti[0])] == namelists[i][int(sti[i][0])]
        _assert_rank_equal_tieaware(ti, ts, sti[i], sts[i])


def test_batched_vmap_matches_sharded(window_batch):
    graphs, _ = window_batch
    cfg = MicroRankConfig()
    mesh = make_mesh((2, 4))
    stacked = stack_window_graphs(graphs, shard_multiple=4)
    sti, sts, _ = rank_windows_sharded(
        jax.tree.map(jnp.asarray, stacked), cfg.pagerank, cfg.spectrum, mesh
    )
    bti, bts, _ = rank_windows_batched(stacked, cfg.pagerank, cfg.spectrum)
    for b in range(np.asarray(bti).shape[0]):
        _assert_rank_equal_tieaware(bti[b], bts[b], sti[b], sts[b])
    fin = np.isfinite(np.asarray(bts))
    rel = np.abs(np.asarray(sts)[fin] - np.asarray(bts)[fin]) / np.maximum(
        np.abs(np.asarray(bts)[fin]), 1e-9
    )
    assert rel.max() < 1e-4


def test_sharded_csr_matches_coo():
    # The csr kernel under shard_map: each device prefix-sums its entry
    # block with clamped row ranges; psum'd partials must equal the coo
    # path's segment sums (f32 reassociation tolerance on scores).
    cfg = MicroRankConfig()
    csr_graphs = []
    for seed in (1, 2, 3, 4):
        case = generate_case(
            SyntheticConfig(n_operations=20, n_traces=100, seed=seed)
        )
        nrm, abn = partition_case(case)
        graph, _, _, _ = build_window_graph(
            case.abnormal, nrm, abn, aux="all"
        )
        csr_graphs.append(graph)
    mesh = make_mesh((2, 4))
    stacked = stack_window_graphs(csr_graphs, shard_multiple=4)
    jstacked = jax.tree.map(jnp.asarray, stacked)
    ci, cs, _ = rank_windows_sharded(
        jstacked, cfg.pagerank, cfg.spectrum, mesh, "csr"
    )
    oi, os_, _ = rank_windows_sharded(
        jstacked, cfg.pagerank, cfg.spectrum, mesh, "coo"
    )
    for b in range(len(csr_graphs)):
        assert int(ci[b][0]) == int(oi[b][0])
        assert set(np.asarray(ci[b]).tolist()) == set(
            np.asarray(oi[b]).tolist()
        )


def test_compensated_psum_cross_shard_parity():
    """The ROADMAP compensated-scan item, evaluated for the coo path
    (PR 5). Entry-axis sharding splits a row's entries at fixed block
    boundaries, so the cross-shard combine reassociates vs the
    single-device segment sum — superficially the csr prefix-scan bug's
    shape. The evaluation's conclusion (pinned here): the combine order
    is NOT the dominant rounding source — the per-shard partials carry
    their own f32 rounding that no combine fix recovers — so the
    compensated all-gather TwoSum fold (opt-in,
    PageRankConfig.compensated_psum) and the plain psum must BOTH match
    the single-device coo ranking within the same small tolerance,
    across two shard counts. Measured drift ~1.7e-6 either way; the
    regression bound leaves ~30x headroom."""
    import dataclasses

    cfg = MicroRankConfig()
    assert not cfg.pagerank.compensated_psum  # evaluated, default off
    graphs = []
    for seed in (5, 6, 7, 8):
        case = generate_case(
            SyntheticConfig(n_operations=20, n_traces=100, seed=seed)
        )
        nrm, abn = partition_case(case)
        graph, _, _, _ = build_window_graph(case.abnormal, nrm, abn)
        graphs.append(graph)
    single = rank_windows_batched(
        stack_window_graphs(graphs), cfg.pagerank, cfg.spectrum, "coo"
    )
    for compensated in (False, True):
        pk = dataclasses.replace(
            cfg.pagerank, compensated_psum=compensated
        )
        for shards in (4, 8):
            mesh = make_mesh((1, shards))
            stacked = stack_window_graphs(graphs, shard_multiple=shards)
            sti, sts, _ = rank_windows_sharded(
                jax.tree.map(jnp.asarray, stacked),
                pk,
                cfg.spectrum,
                mesh,
                "coo",
            )
            for b in range(len(graphs)):
                n = int(single[2][b])
                a = np.asarray(single[1][b][:n], np.float64)
                s = np.asarray(sts[b][:n], np.float64)
                fin = np.isfinite(a) & np.isfinite(s)
                rel = np.abs(a[fin] - s[fin]) / np.maximum(
                    np.abs(a[fin]), 1e-12
                )
                assert rel.max() < 5e-5, (compensated, shards, b, rel.max())
                _assert_rank_equal_tieaware(
                    single[0][b], single[1][b], sti[b], sts[b], rtol=5e-5
                )


def test_shard_only_mesh(window_batch):
    # Pure graph-parallelism: 1 window across all 8 devices.
    graphs, namelists = window_batch
    cfg = MicroRankConfig()
    mesh = make_mesh((1, 8))
    stacked = stack_window_graphs(graphs[:1], shard_multiple=8)
    sti, _, _ = rank_windows_sharded(
        jax.tree.map(jnp.asarray, stacked), cfg.pagerank, cfg.spectrum, mesh
    )
    ti, _, _ = rank_window_device(
        jax.tree.map(jnp.asarray, graphs[0]), cfg.pagerank, cfg.spectrum
    )
    assert namelists[0][int(ti[0])] == namelists[0][int(sti[0][0])]


def test_mesh_helpers():
    m = make_mesh((2, 4))
    assert m.devices.shape == (2, 4)
    assert m.axis_names == ("windows", "shard")
    m1 = single_axis_mesh(8)
    assert m1.devices.shape == (8,)
    with pytest.raises(ValueError):
        make_mesh((3, 4, 5), ("a", "b"))
    with pytest.raises(ValueError):
        make_mesh((1024,), ("shard",))


def test_graft_entry_points():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out[0].shape == out[1].shape
    # Small shapes for the unit suite; the driver runs the full
    # config-2-sized dryrun (defaults) itself.
    mod.dryrun_multichip(8, n_operations=48, target_spans=1_000)


def test_table_rca_sharded_matches_default(tmp_path):
    # RuntimeConfig.mesh_shape routes TableRCA ranking through shard_map.
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.config import RuntimeConfig
    from microrank_tpu.pipeline import TableRCA

    case = generate_case(
        SyntheticConfig(n_operations=20, n_traces=120, seed=5,
                        n_kinds=24, child_keep_prob=0.6)
    )
    case.normal.to_csv(tmp_path / "n.csv", index=False)
    case.abnormal.to_csv(tmp_path / "a.csv", index=False)
    normal = native.load_span_table(tmp_path / "n.csv")
    abnormal = native.load_span_table(tmp_path / "a.csv")

    plain = TableRCA(MicroRankConfig())
    plain.fit_baseline(normal)
    r_plain = plain.run(abnormal)

    a = next(r for r in r_plain if r.ranking)
    # Both shard-capable kernels route through the pipeline's mesh branch.
    for kernel in ("auto", "csr"):
        cfg = MicroRankConfig(
            runtime=RuntimeConfig(mesh_shape=(8,), kernel=kernel)
        )
        sharded = TableRCA(cfg)
        sharded.fit_baseline(normal)
        r_sharded = sharded.run(abnormal)
        b = next(r for r in r_sharded if r.ranking)
        assert [n for n, _ in a.ranking] == [n for n, _ in b.ranking], kernel


def test_batched_with_convergence_tol(window_batch):
    # lax.while_loop under vmap runs lockstep until every window's
    # vectors converge; results must match per-window tol ranking.
    from microrank_tpu.config import PageRankConfig

    graphs, namelists = window_batch
    cfg = MicroRankConfig(
        pagerank=PageRankConfig(iterations=100, tol=1e-6)
    )
    stacked = stack_window_graphs(graphs)
    bti, _, _ = rank_windows_batched(stacked, cfg.pagerank, cfg.spectrum)
    for i, g in enumerate(graphs):
        ti, _, _ = rank_window_device(
            jax.tree.map(jnp.asarray, g), cfg.pagerank, cfg.spectrum
        )
        assert int(np.asarray(ti)[0]) == int(np.asarray(bti[i])[0])


def test_sharded_packed_matches_single_device():
    # The trace-sharded MXU bitmap kernel: bitmap column blocks + a
    # distributed rv with one psum per iteration must match the
    # single-device packed kernel (tie-aware: the wider stacked trace
    # padding changes reduction shapes).
    from microrank_tpu.config import PageRankConfig

    cfg = MicroRankConfig()
    graphs, namelists = [], []
    for seed in (1, 2, 3, 4):
        case = generate_case(
            SyntheticConfig(n_operations=20, n_traces=100, seed=seed)
        )
        nrm, abn = partition_case(case)
        graph, names, _, _ = build_window_graph(
            case.abnormal, nrm, abn, aux="all"
        )
        graphs.append(graph)
        namelists.append(names)
    mesh = make_mesh((2, 4))
    stacked = stack_window_graphs(graphs, shard_multiple=4, trace_multiple=32)
    sti, sts, _ = rank_windows_sharded(
        jax.tree.map(jnp.asarray, stacked), cfg.pagerank, cfg.spectrum,
        mesh, "packed",
    )
    for i, g in enumerate(graphs):
        ti, ts, _ = rank_window_device(
            jax.tree.map(jnp.asarray, g), cfg.pagerank, cfg.spectrum,
            None, "packed",
        )
        assert namelists[i][int(ti[0])] == namelists[i][int(sti[i][0])]
        _assert_rank_equal_tieaware(ti, ts, sti[i], sts[i])

    # Convergence-tol path: the while_loop predicate pmaxes the sharded
    # rv delta so all shards agree on when to stop.
    tol_cfg = PageRankConfig(iterations=100, tol=1e-6)
    tti, _, _ = rank_windows_sharded(
        jax.tree.map(jnp.asarray, stacked), tol_cfg, cfg.spectrum,
        mesh, "packed",
    )
    for i in range(len(graphs)):
        assert int(np.asarray(tti[i])[0]) == int(np.asarray(sti[i])[0])


def test_sharded_packed_rejects_misaligned_traces():
    # Without trace_multiple=8*S the packed sharded kernel must fail
    # loudly with stacking instructions, not shard garbage.
    cfg = MicroRankConfig()
    case = generate_case(
        SyntheticConfig(n_operations=20, n_traces=70, seed=1)
    )
    nrm, abn = partition_case(case)
    # Exact padding gives an odd trace extent that cannot divide 8*S.
    graph, _, _, _ = build_window_graph(
        case.abnormal, nrm, abn, aux="all", pad_policy="exact"
    )
    mesh = make_mesh((1, 8))
    stacked = stack_window_graphs([graph], shard_multiple=8)
    assert stacked.normal.kind.shape[-1] % 64 != 0
    with pytest.raises(ValueError, match="trace_multiple"):
        rank_windows_sharded(
            jax.tree.map(jnp.asarray, stacked), cfg.pagerank,
            cfg.spectrum, mesh, "packed",
        )


def test_table_rca_batched_on_2d_mesh(tmp_path):
    # batch_windows + a (2, 4) mesh: the batch splits over the windows
    # axis while each window's graph shards over the shard axis — the
    # rankings must match the single-device batched mode.
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.config import RuntimeConfig
    from microrank_tpu.pipeline import TableRCA
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(n_operations=16, n_traces=80, seed=4), 3, [0, 1, 2]
    )
    tl.normal.to_csv(tmp_path / "n.csv", index=False)
    tl.timeline.to_csv(tmp_path / "a.csv", index=False)
    normal = native.load_span_table(tmp_path / "n.csv")
    timeline = native.load_span_table(tmp_path / "a.csv")

    plain = TableRCA(MicroRankConfig())
    plain.fit_baseline(normal)
    r_plain = plain.run(timeline, batch_windows=True)
    expected = [
        [n for n, _ in r.ranking] if r.ranking else None for r in r_plain
    ]
    assert any(e for e in expected)

    meshed = TableRCA(
        MicroRankConfig(runtime=RuntimeConfig(mesh_shape=(2, 4)))
    )
    meshed.fit_baseline(normal)
    r_mesh = meshed.run(timeline, batch_windows=True)
    got = [
        [n for n, _ in r.ranking] if r.ranking else None for r in r_mesh
    ]
    assert got == expected

    # Per-window dispatch on a windows-axis>1 mesh still fails clearly.
    with pytest.raises(ValueError, match="batch_windows"):
        meshed.run(timeline)


def test_sharded_checked_matches_unchecked(window_batch):
    """device_checks on the mesh path (PR 7): the checkify epilogue
    returns the untouched sharded outputs, and a poisoned batch raises
    JaxRuntimeError naming the failed check."""
    import jax.numpy as jnp
    from jax.experimental import checkify

    from microrank_tpu.parallel import (
        rank_windows_sharded_checked,
        rank_windows_sharded_checked_traced,
    )
    from microrank_tpu.parallel.sharded_rank import (
        _sharded_checked_traced_jit,
        rank_windows_sharded,
        rank_windows_sharded_traced,
        stage_sharded,
    )

    graphs, _ = window_batch
    cfg = MicroRankConfig()
    mesh = make_mesh((2, 4))
    batched = stage_sharded(graphs, mesh, "coo")
    for checked_fn, plain_fn in (
        (rank_windows_sharded_checked, rank_windows_sharded),
        (rank_windows_sharded_checked_traced, rank_windows_sharded_traced),
    ):
        outs_c = checked_fn(
            batched, cfg.pagerank, cfg.spectrum, mesh, "coo"
        )
        outs_p = plain_fn(
            batched, cfg.pagerank, cfg.spectrum, mesh, "coo"
        )
        for a, b in zip(outs_c, outs_p):
            assert np.array_equal(np.asarray(a), np.asarray(b))
    # Poisoned scores trip the device-side check.
    outs = rank_windows_sharded_traced(
        batched, cfg.pagerank, cfg.spectrum, mesh, "coo"
    )
    bad = (
        outs[0],
        jnp.full_like(jnp.asarray(outs[1]), jnp.nan),
        outs[2], outs[3], outs[4],
    )
    err, _ = _sharded_checked_traced_jit()(*bad)
    with pytest.raises(checkify.JaxRuntimeError, match="non-finite"):
        checkify.check_error(err)


def test_table_rca_sharded_device_checks_keeps_convergence(tmp_path):
    """Mirror of test_convergence_trace_survives_device_checks (PR 6)
    for the SHARDED path: device_checks + convergence_trace on a mesh
    ranks through rank_windows_sharded_checked_traced — telemetry must
    flow, not silently drop, and the ranking must match the unchecked
    mesh run."""
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.config import RuntimeConfig
    from microrank_tpu.pipeline import TableRCA

    case = generate_case(
        SyntheticConfig(n_operations=20, n_traces=120, seed=5,
                        n_kinds=24, child_keep_prob=0.6)
    )
    case.normal.to_csv(tmp_path / "n.csv", index=False)
    case.abnormal.to_csv(tmp_path / "a.csv", index=False)
    normal = native.load_span_table(tmp_path / "n.csv")
    abnormal = native.load_span_table(tmp_path / "a.csv")

    plain = TableRCA(
        MicroRankConfig(runtime=RuntimeConfig(mesh_shape=(8,)))
    )
    plain.fit_baseline(normal)
    r_plain = plain.run(abnormal)
    a = next(r for r in r_plain if r.ranking)

    checked = TableRCA(
        MicroRankConfig(
            runtime=RuntimeConfig(
                mesh_shape=(8,),
                device_checks=True,
                convergence_trace=True,
            )
        )
    )
    checked.fit_baseline(normal)
    r_checked = checked.run(abnormal)
    ranked = [r for r in r_checked if r.ranking]
    assert ranked, "no window ranked — fixture drifted"
    b = ranked[0]
    assert [n for n, _ in a.ranking] == [n for n, _ in b.ranking]
    for r in ranked:
        assert r.rank_iterations is not None
        assert r.rank_residual is not None


def test_sparse_allreduce_cross_shard_parity():
    """The ISSUE-11 sparse-allreduce evaluation (arxiv 1312.3020),
    pinned: with the cap at the full axis (``sparse_allreduce_cap=0``)
    the top-cap (index, value) exchange keeps EVERY entry, so the
    sparse combine must reproduce the dense-psum sharded ranking
    bitwise — the only difference is the scatter-add reassociation,
    which lands identically here. The evaluation's conclusion (see
    DESIGN.md "Sparse allreduce evaluation"): at this workload's [V]/
    [T] vector sizes the exchange costs MORE than the dense psum
    (measured ~1.9x per dispatch on the (2,4) CPU mesh) and an
    undersized cap silently drops true support — default stays OFF."""
    import dataclasses

    cfg = MicroRankConfig()
    assert not cfg.pagerank.sparse_allreduce  # evaluated, default off
    graphs = []
    for seed in (9, 10):
        case = generate_case(
            SyntheticConfig(n_operations=20, n_traces=100, seed=seed)
        )
        nrm, abn = partition_case(case)
        graph, _, _, _ = build_window_graph(case.abnormal, nrm, abn)
        graphs.append(graph)
    mesh = make_mesh((1, 4))
    stacked = jax.tree.map(
        jnp.asarray, stack_window_graphs(graphs, shard_multiple=4)
    )
    dense = rank_windows_sharded(
        stacked, cfg.pagerank, cfg.spectrum, mesh, "coo"
    )
    sparse = rank_windows_sharded(
        stacked,
        dataclasses.replace(cfg.pagerank, sparse_allreduce=True),
        cfg.spectrum,
        mesh,
        "coo",
    )
    for d, s in zip(dense, sparse):
        assert np.array_equal(np.asarray(d), np.asarray(s))


def test_donated_sharded_twin_matches_and_is_consumed(window_batch):
    """The donated twins of the sharded programs (ROADMAP item 3's
    "untested donation" thread): donation is an aliasing HINT — the
    donated program must produce bit-identical rankings — and on
    donation-capable backends the staged input buffers must actually be
    consumed (CPU ignores donation with a warning; parity still
    holds)."""
    import warnings

    from microrank_tpu.parallel.sharded_rank import (
        resolve_sharded_rank_fn,
        sharded_donated_entry,
    )

    graphs, _ = window_batch
    cfg = MicroRankConfig()
    mesh = make_mesh((2, 4))
    stacked = stack_window_graphs(graphs, shard_multiple=4)
    ref = rank_windows_sharded(
        jax.device_put(stacked), cfg.pagerank, cfg.spectrum, mesh, "coo"
    )
    ref = jax.device_get(ref)
    for conv_trace in (False, True):
        donated_fn = resolve_sharded_rank_fn(
            conv_trace, device_checks=False, donate=True
        )
        assert donated_fn is sharded_donated_entry(conv_trace)
        donated_in = jax.device_put(stacked)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # CPU: donation unusable
            out = jax.device_get(
                donated_fn(
                    donated_in, cfg.pagerank, cfg.spectrum, mesh, "coo"
                )
            )
        for a, b in zip(ref, out[:3]):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        if jax.default_backend() not in ("cpu",):
            leaves = jax.tree.leaves(donated_in)
            assert any(x.is_deleted() for x in leaves)
    # The undonated resolution is unchanged by the new parameter.
    assert (
        resolve_sharded_rank_fn(False, False, donate=False)
        is rank_windows_sharded
    )
