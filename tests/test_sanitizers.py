"""In-program sanitizers (SURVEY.md §5): checkify device checks and
donated-buffer correctness — the two planned items the aux row was
missing through round 3."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import partition_case
from microrank_tpu.config import MicroRankConfig
from microrank_tpu.graph import build_window_graph
from microrank_tpu.rank_backends.jax_tpu import (
    rank_window_checked,
    rank_window_device,
)


def _graph(case):
    nrm, abn = partition_case(case)
    graph, names, _, _ = build_window_graph(case.abnormal, nrm, abn)
    return graph, names


def test_checked_rank_matches_unchecked(small_case):
    cfg = MicroRankConfig()
    graph, _ = _graph(small_case)
    dg = jax.tree.map(jnp.asarray, graph)
    ref = rank_window_device(dg, cfg.pagerank, cfg.spectrum, None, "coo")
    got = rank_window_checked(dg, cfg.pagerank, cfg.spectrum, "coo")
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_checked_rank_traps_nonfinite(small_case):
    # Poison one incidence value so a division feeds NaN into the
    # ranking; the in-program check must name the invariant instead of
    # letting NaN flow to the host.
    from jax.experimental import checkify

    cfg = MicroRankConfig()
    graph, _ = _graph(small_case)
    bad_sr = np.asarray(graph.abnormal.sr_val).copy()
    bad_sr[0] = np.nan
    poisoned = graph._replace(
        abnormal=graph.abnormal._replace(sr_val=bad_sr)
    )
    with pytest.raises(checkify.JaxRuntimeError, match="non-finite"):
        rank_window_checked(
            jax.tree.map(jnp.asarray, poisoned),
            cfg.pagerank,
            cfg.spectrum,
            "coo",
        )


def test_backend_device_checks_flag(small_case):
    # RuntimeConfig.device_checks routes JaxBackend through the checked
    # program and must not change the ranking.
    from dataclasses import replace

    from microrank_tpu.rank_backends import get_backend

    nrm, abn = partition_case(small_case)
    cfg = MicroRankConfig()
    top_a, sc_a = get_backend(cfg).rank_window(small_case.abnormal, nrm, abn)
    cfg_c = cfg.replace(runtime=replace(cfg.runtime, device_checks=True))
    top_b, sc_b = get_backend(cfg_c).rank_window(
        small_case.abnormal, nrm, abn
    )
    assert top_a == top_b
    np.testing.assert_allclose(sc_a, sc_b, rtol=1e-6)


def test_donated_graph_buffers_rank_identically(small_case):
    # Buffer donation lets XLA reuse the staged graph's memory for
    # outputs; the ranking must be unchanged. (CPU ignores donation with
    # a warning — the assertion is still exact there; on TPU this
    # exercises real aliasing.)
    cfg = MicroRankConfig()
    graph, _ = _graph(small_case)
    ref = rank_window_device(
        jax.tree.map(jnp.asarray, graph),
        cfg.pagerank,
        cfg.spectrum,
        None,
        "coo",
    )
    donated_fn = jax.jit(
        lambda g: __import__(
            "microrank_tpu.rank_backends.jax_tpu", fromlist=["x"]
        ).rank_window_core(g, cfg.pagerank, cfg.spectrum, None, "coo"),
        donate_argnums=(0,),
    )
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # CPU "donation not implemented"
        got = donated_fn(jax.tree.map(jnp.asarray, graph))
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_pipeline_lane_honors_device_checks(small_case, tmp_path):
    case = small_case
    # The table/pipeline lane (the path bench and the CLI use) must
    # route device_checks through the checked program, not ignore it.
    from dataclasses import replace

    import pytest as _pytest

    from microrank_tpu.native import native_available
    from microrank_tpu.pipeline import run_rca_native

    if not native_available():
        _pytest.skip("native lane unavailable")
    case.normal.to_csv(tmp_path / "normal.csv", index=False)
    case.abnormal.to_csv(tmp_path / "abnormal.csv", index=False)
    cfg = MicroRankConfig()
    base = run_rca_native(
        tmp_path / "normal.csv", tmp_path / "abnormal.csv", cfg,
        tmp_path / "out_base",
    )
    cfg_c = cfg.replace(runtime=replace(cfg.runtime, device_checks=True))
    checked = run_rca_native(
        tmp_path / "normal.csv", tmp_path / "abnormal.csv", cfg_c,
        tmp_path / "out_checked",
    )
    assert [r.ranking for r in checked] == [r.ranking for r in base]
    assert any(r.ranking for r in base)
