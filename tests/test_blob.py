"""Blob staging (rank_backends.blob): the single-transfer device path.

The pack/unpack pair must be a bit-exact identity over every leaf dtype
(float32/int32/uint8/bool and the 0-d extents), and the blob rank program
must return exactly what the per-leaf-staged program returns — same jitted
math, different transport.
"""

import jax
import numpy as np
import pytest

from conftest import partition_case
from microrank_tpu.config import MicroRankConfig
from microrank_tpu.graph.build import aux_for_kernel, build_window_graph
from microrank_tpu.rank_backends.blob import (
    pack_graph_blob,
    rank_window_blob_device,
    unpack_graph_blob,
)
from microrank_tpu.rank_backends.jax_tpu import (
    choose_kernel,
    device_subset,
    rank_window_device,
)


def _graph_for(case, kernel="auto", **build_kw):
    nrm, abn = partition_case(case)
    graph, op_names, _, _ = build_window_graph(
        case.abnormal, nrm, abn, aux=aux_for_kernel(kernel), **build_kw
    )
    return graph, op_names


def test_blob_roundtrip_bit_exact(small_case):
    graph, _ = _graph_for(small_case)
    blob, layout = pack_graph_blob(graph)
    assert blob.dtype == np.uint32
    out = jax.jit(unpack_graph_blob, static_argnums=1)(blob, layout)
    for part_name in ("normal", "abnormal"):
        src, dst = getattr(graph, part_name), getattr(out, part_name)
        for f, a, b in zip(src._fields, src, dst):
            a, b = np.asarray(a), np.asarray(b)
            assert a.shape == b.shape, f"{part_name}.{f} shape"
            assert a.dtype == b.dtype, f"{part_name}.{f} dtype"
            # Bitwise equality, including float32 (same-width bitcasts).
            np.testing.assert_array_equal(
                np.atleast_1d(a).view(np.uint8),
                np.atleast_1d(b).view(np.uint8),
                err_msg=f"{part_name}.{f}",
            )


def test_blob_roundtrip_stripped_fields(small_case):
    # device_subset replaces unused leaves with 0-width arrays; the blob
    # must carry them (0 words) and restore the 0-width shapes.
    graph, _ = _graph_for(small_case, kernel="packed")
    sub = device_subset(graph, "packed")
    blob, layout = pack_graph_blob(sub)
    out = jax.jit(unpack_graph_blob, static_argnums=1)(blob, layout)
    assert out.normal.inc_op.shape == sub.normal.inc_op.shape
    assert int(np.asarray(out.abnormal.n_traces)) == int(
        np.asarray(sub.abnormal.n_traces)
    )


@pytest.mark.parametrize("kernel", ["packed", "csr", "pcsr", "coo"])
def test_blob_rank_matches_per_leaf_staging(small_case, kernel):
    cfg = MicroRankConfig()
    graph, _ = _graph_for(small_case, kernel=kernel)
    if kernel == "packed" and choose_kernel(graph) != "packed":
        pytest.skip("packed aux not built at this size")
    sub = device_subset(graph, kernel)
    ref = rank_window_device(
        jax.device_put(sub), cfg.pagerank, cfg.spectrum, None, kernel
    )
    blob, layout = pack_graph_blob(sub)
    got = rank_window_blob_device(
        jax.device_put(blob), layout, cfg.pagerank, cfg.spectrum, None, kernel
    )
    # Same ranking and count exactly; scores only to float32 closeness —
    # the blob program is a different XLA program, so fusion may reorder
    # float reductions by a ulp (the unpack itself is bit-exact, see
    # test_blob_roundtrip_bit_exact).
    np.testing.assert_array_equal(np.asarray(ref[0]), np.asarray(got[0]))
    np.testing.assert_allclose(
        np.asarray(ref[1]), np.asarray(got[1]), rtol=1e-5
    )
    assert int(ref[2]) == int(got[2])


def test_blob_roundtrip_exact_padding(small_case):
    # pad_policy="exact" produces odd array lengths (non-multiple-of-4
    # byte counts for uint8/bool leaves) — the word-padding path must
    # still round-trip bit-exactly.
    graph, _ = _graph_for(small_case, pad_policy="exact")
    blob, layout = pack_graph_blob(graph)
    out = jax.jit(unpack_graph_blob, static_argnums=1)(blob, layout)
    for part_name in ("normal", "abnormal"):
        src, dst = getattr(graph, part_name), getattr(out, part_name)
        for f, a, b in zip(src._fields, src, dst):
            a, b = np.asarray(a), np.asarray(b)
            assert a.shape == b.shape and a.dtype == b.dtype, f
            np.testing.assert_array_equal(
                np.atleast_1d(a).view(np.uint8),
                np.atleast_1d(b).view(np.uint8),
                err_msg=f"{part_name}.{f}",
            )
