"""Structural parity: array COO build vs faithful dict build.

Densifies the padded COO arrays and compares them entry-for-entry with the
reference-semantics matrices built from the dicts — on synthetic data,
for both partitions. This pins the whole C8/C9/C10 re-design (SURVEY.md)
to the reference's exact values.
"""

import numpy as np

from conftest import partition_case
from microrank_tpu.graph import (
    build_detect_batch,
    build_window_graph,
    pagerank_graph_dicts,
)
from microrank_tpu.detect import compute_slo
from microrank_tpu.rank_backends import numpy_ref


def _densify(part, op_names, trace_list_local):
    """Rebuild dense p_ss/p_sr/p_rs (op axis = window vocab) from COO."""
    v = len(op_names)
    t = int(part.n_traces)
    n_inc, n_ss = int(part.n_inc), int(part.n_ss)
    p_sr = np.zeros((v, t), dtype=np.float32)
    p_rs = np.zeros((t, v), dtype=np.float32)
    p_ss = np.zeros((v, v), dtype=np.float32)
    p_sr[part.inc_op[:n_inc], part.inc_trace[:n_inc]] = part.sr_val[:n_inc]
    p_rs[part.inc_trace[:n_inc], part.inc_op[:n_inc]] = part.rs_val[:n_inc]
    p_ss[part.ss_child[:n_ss], part.ss_parent[:n_ss]] = part.ss_val[:n_ss]
    return p_ss, p_sr, p_rs


def test_array_build_matches_dict_build(small_case):
    case = small_case
    nrm, abn = partition_case(case)
    assert nrm and abn
    graph, op_names, norm_traces, abn_traces = build_window_graph(
        case.abnormal, nrm, abn
    )
    op_pos = {n: i for i, n in enumerate(op_names)}

    for part, ids, local_traces in (
        (graph.normal, nrm, norm_traces),
        (graph.abnormal, abn, abn_traces),
    ):
        dicts = pagerank_graph_dicts(ids, case.abnormal)
        ref_ss, ref_sr, ref_rs, nodes, traces = numpy_ref.build_matrices(
            dicts[0], dicts[1], dicts[2]
        )
        assert int(part.n_ops) == len(nodes)
        assert int(part.n_traces) == len(traces)
        assert sorted(local_traces) == sorted(traces)

        got_ss, got_sr, got_rs = _densify(part, op_names, local_traces)
        # Remap reference matrices into (window-vocab, local-trace) indexing.
        op_map = np.array([op_pos[n] for n in nodes])
        tr_pos = {t: i for i, t in enumerate(local_traces)}
        tr_map = np.array([tr_pos[t] for t in traces])

        exp_sr = np.zeros_like(got_sr)
        exp_sr[np.ix_(op_map, tr_map)] = ref_sr
        np.testing.assert_array_equal(got_sr, exp_sr)

        exp_rs = np.zeros_like(got_rs)
        exp_rs[np.ix_(tr_map, op_map)] = ref_rs
        np.testing.assert_array_equal(got_rs, exp_rs)

        exp_ss = np.zeros_like(got_ss)
        exp_ss[np.ix_(op_map, op_map)] = ref_ss
        np.testing.assert_array_equal(got_ss, exp_ss)

        # Kind sizes match the reference's column-equality dedup.
        ref_kind = numpy_ref.compute_kind_list(ref_sr)
        got_kind = part.kind[: len(traces)]
        exp_kind = np.zeros(len(traces))
        exp_kind[tr_map] = ref_kind
        np.testing.assert_array_equal(got_kind, exp_kind.astype(np.int32))

        # Coverage counts (trace_num_list).
        cov = {
            op: int(np.count_nonzero(ref_sr[i]))
            for i, op in enumerate(nodes)
        }
        for op, c in cov.items():
            assert int(part.cov_unique[op_pos[op]]) == c


def test_detect_batch_roundtrip(small_case):
    case = small_case
    vocab, baseline = compute_slo(case.normal)
    batch, trace_ids = build_detect_batch(case.abnormal, vocab)
    assert int(batch.n_traces) == case.abnormal["traceID"].nunique()
    assert int(batch.n_spans) == len(case.abnormal)
    # Padding is inert: op = -1, duration = 0.
    n = int(batch.n_spans)
    assert (batch.op[n:] == -1).all()
    assert (batch.duration_us[n:] == 0).all()


def test_kind_hash_path_matches_exact(monkeypatch):
    # Large windows switch _trace_kinds from exact padded-row np.unique to
    # O(E) 128-bit set hashing; both must yield identical kind sizes.
    import microrank_tpu.graph.build as build_mod
    from microrank_tpu.graph import build_window_graph
    from microrank_tpu.testing import SyntheticConfig, generate_case
    from conftest import partition_case

    case = generate_case(
        SyntheticConfig(n_operations=30, n_traces=250, n_kinds=12, seed=17)
    )
    nrm, abn = partition_case(case)
    if not (nrm and abn):
        pytest.skip("window did not partition")
    g_exact, _, _, _ = build_window_graph(case.abnormal, nrm, abn)
    monkeypatch.setattr(build_mod, "_DENSE_KIND_BUDGET", 1)
    g_hash, _, _, _ = build_window_graph(case.abnormal, nrm, abn)
    for side in ("normal", "abnormal"):
        a, b = getattr(g_exact, side), getattr(g_hash, side)
        np.testing.assert_array_equal(a.kind, b.kind, err_msg=side)


def test_pad_to_pow2q_contract():
    # pow2q buckets: >= n, >= min_pad, multiples of 8 once >= 64, at
    # most 25% waste past 64, and monotone in n.
    from microrank_tpu.graph.structures import pad_to

    prev = 0
    for n in range(1, 5000):
        p = pad_to(n, "pow2q")
        assert p >= n
        assert p >= 8
        if p >= 64:
            assert p % 8 == 0
        if n >= 64:
            assert p <= n * 1.25 + 8, (n, p)
        assert p >= prev
        prev = p
    # min_pad floor respected even where quarter steps would undershoot.
    assert pad_to(5, "pow2q", min_pad=128) == 128
    assert pad_to(200, "pow2q", min_pad=256) == 256


def test_resolve_aux_modes():
    from microrank_tpu.graph.build import (
        packed_bits_bytes,
        resolve_aux,
    )

    v, t_pads = 1024, (2048, 256)
    bits = packed_bits_bytes(v, t_pads)
    big = bits * 4 + 1  # budget whose quarter fits the bitmaps
    small = bits * 4 - 1  # quarter just misses
    # Single-device auto: packed inside the bitmap budget, the
    # partition-centric fallback past it.
    assert resolve_aux("auto", v, t_pads, big) == "packed"
    assert resolve_aux("auto", v, t_pads, small) == "pcsr"
    # Sharded auto_all: EVERY family inside the budget (so the
    # per-shard kernel choice can fall back), pcsr past it.
    assert resolve_aux("auto_all", v, t_pads, big) == "all"
    assert resolve_aux("auto_all", v, t_pads, small) == "pcsr"
    # Explicit modes pass through.
    for mode in ("packed", "csr", "pcsr", "all", "none"):
        assert resolve_aux(mode, v, t_pads, small) == mode


def test_aux_for_kernel_sharded_promotion():
    from microrank_tpu.graph.build import aux_for_kernel

    assert aux_for_kernel("auto") == "auto"
    assert aux_for_kernel("auto", sharded=True) == "auto_all"
    # Non-auto kernels are unaffected by the sharded hint.
    assert aux_for_kernel("packed", sharded=True) == "packed"
    assert aux_for_kernel("csr", sharded=True) == "csr"
    assert aux_for_kernel("dense", sharded=True) == "none"
