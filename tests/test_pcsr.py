"""Partition-centric kernel (kernel="pcsr") — build views, parity, and
dispatch paths.

The pcsr kernel is the memory-bounded fallback for windows whose
per-trace bitmaps blow the dense budget (resolve_aux past the bitmap
budget; Partition-Centric PageRank, arxiv 1709.07122). These tests pin:

* the binned views reconstruct the incidence exactly (build unit test,
  numpy lane and native lane array-identical);
* SCORES and tie-aware top-k against the coo kernel and the float64
  sparse / dense numpy_ref oracles, at the same tolerance ladder as the
  csr collapse-parity suite (f32 at 2e-5, the bf16 rung at 5e-3),
  including the collapsed-duplicate-trace path;
* the vmapped-batch, blob-staged and 2D-mesh sharded dispatches match
  the single-device ranking.
"""

import numpy as np
import pytest

import jax

from microrank_tpu.config import MicroRankConfig
from microrank_tpu.graph.build import (
    PCSR_BLOCK,
    PCSR_PART_TRACES,
    build_window_graph,
    pcsr_auxiliary,
    pcsr_partitions,
    resolve_aux,
)
from microrank_tpu.rank_backends.jax_tpu import (
    choose_kernel,
    device_subset,
    rank_window_device,
)
from microrank_tpu.rank_backends.sparse_oracle import rank_window_sparse
from microrank_tpu.testing import SyntheticConfig, generate_case

from conftest import partition_case

CFG = MicroRankConfig()


@pytest.fixture(scope="module")
def kind_case():
    """Strong kind structure — the collapsed-duplicate-trace path."""
    return generate_case(
        SyntheticConfig(n_operations=60, n_kinds=6, n_traces=400, seed=3)
    )


@pytest.fixture(scope="module")
def graphs(kind_case):
    nrm, abn = partition_case(kind_case)
    g0, names, _, _ = build_window_graph(
        kind_case.abnormal, nrm, abn, aux="all", collapse="off"
    )
    g1, _, _, _ = build_window_graph(
        kind_case.abnormal, nrm, abn, aux="all", collapse="on"
    )
    return g0, g1, names


def _ranked(graph, names, kernel):
    ti, ts, nv = jax.device_get(
        rank_window_device(graph, CFG.pagerank, CFG.spectrum, None, kernel)
    )
    n = int(nv)
    return (
        [names[int(i)] for i in ti[:n]],
        np.asarray(ts[:n], dtype=np.float64),
    )


def test_pcsr_views_reconstruct_incidence(graphs):
    """Scatter the binned forward tables and the ELL slab back into
    (op, trace, value) triples: both must reproduce the live incidence
    exactly (same multiset of entries, values bit-identical)."""
    g0, _, _ = graphs
    for part in (g0.normal, g0.abnormal):
        n_inc = int(part.n_inc)
        v_pad = part.cov_unique.shape[0]
        t_pad = part.kind.shape[0]
        truth = {
            (int(o), int(t)): (float(sv), float(rv))
            for o, t, sv, rv in zip(
                part.inc_op[:n_inc],
                part.inc_trace[:n_inc],
                part.sr_val[:n_inc],
                part.rs_val[:n_inc],
            )
        }
        # Forward tables: walk each (partition, op) block range.
        n_parts, e_blk = part.pc_trace.shape
        assert n_parts == pcsr_partitions(t_pad)
        assert e_blk % PCSR_BLOCK == 0
        seen_fwd = {}
        for p in range(n_parts):
            indptr = part.pc_blk_indptr[p]
            for o in range(v_pad):
                lo, hi = int(indptr[o]) * PCSR_BLOCK, int(
                    indptr[o + 1]
                ) * PCSR_BLOCK
                for e in range(lo, hi):
                    val = float(part.pc_sr_val[p, e])
                    if val == 0.0:
                        continue  # block padding
                    tr = int(part.pc_trace[p, e]) + p * PCSR_PART_TRACES
                    seen_fwd[(o, tr)] = val
        assert seen_fwd == {k: v[0] for k, v in truth.items()}
        # ELL slab.
        seen_bwd = {}
        for t in range(t_pad):
            for w in range(part.pc_ell_op.shape[1]):
                val = float(part.pc_ell_rs[t, w])
                if val == 0.0:
                    continue
                seen_bwd[(int(part.pc_ell_op[t, w]), t)] = val
        assert seen_bwd == {k: v[1] for k, v in truth.items()}


def test_pcsr_empty_partition_build():
    """A partition with zero entries still builds valid (inert) views."""
    out = pcsr_auxiliary(
        np.zeros(0, np.int32),
        np.zeros(0, np.int32),
        np.zeros(0, np.float32),
        np.zeros(0, np.float32),
        0,
        8,
        16,
    )
    pc_trace, pc_sr, blk, ell_op, ell_rs = out
    assert pc_trace.shape[0] == pcsr_partitions(16)
    assert not blk[:, -1].any()
    assert not ell_rs.any()


@pytest.mark.parametrize("oracle", ["coo", "sparse_f64", "numpy_ref", "bf16"])
def test_pcsr_parity_ladder(graphs, kind_case, oracle):
    """pcsr SCORES + tie-aware top-k against the oracle ladder: exact
    f32 kernel (coo) at reassociation tolerance, the float64 sparse
    oracle and the dense numpy_ref reference on names, and the bf16
    rung at bf16 tolerance — on both the uncollapsed and the
    kind-collapsed build (same ladder as the csr collapse-parity
    suite)."""
    g0, g1, names = graphs
    for g in (g0, g1):
        ranked, scores = _ranked(g, names, "pcsr")
        if oracle == "coo":
            base, base_scores = _ranked(g0, names, "coo")
            assert ranked == base
            np.testing.assert_allclose(
                scores, base_scores, rtol=2e-5, atol=1e-5
            )
        elif oracle == "sparse_f64":
            top_o, _ = rank_window_sparse(
                g0, names, CFG.pagerank, CFG.spectrum
            )
            assert ranked[:5] == top_o[:5]
        elif oracle == "numpy_ref":
            from microrank_tpu.rank_backends import NumpyRefBackend

            nrm, abn = partition_case(kind_case)
            top_r, _ = NumpyRefBackend(CFG).rank_window(
                kind_case.abnormal, nrm, abn
            )
            assert ranked[: len(top_r[:5])] == top_r[:5]
        else:  # bf16 rung: packed_bf16 on the same build
            b_names, b_scores = _ranked(g, names, "packed_bf16")
            assert ranked[:5] == b_names[:5]
            np.testing.assert_allclose(
                scores, b_scores, rtol=5e-3, atol=1e-4
            )


def test_resolve_past_budget_builds_pcsr_and_ranks(kind_case):
    """aux='auto' past the bitmap budget builds ONLY the pcsr views, and
    choose_kernel picks pcsr — policy and presence stay coherent."""
    nrm, abn = partition_case(kind_case)
    graph, names, _, _ = build_window_graph(
        kind_case.abnormal, nrm, abn, aux="auto", dense_budget_bytes=64
    )
    assert graph.normal.cov_bits.shape[-1] == 0
    assert graph.normal.inc_indptr_op.shape[-1] == 0
    assert graph.normal.pc_trace.shape[-1] > 0
    assert choose_kernel(graph, dense_budget_bytes=64) == "pcsr"
    ranked, _ = _ranked(graph, names, "pcsr")
    base, _ = _ranked(graph, names, "coo")
    assert ranked == base


def test_pcsr_device_subset_strips_everything_else(graphs):
    g0, _, _ = graphs
    sub = device_subset(g0, "pcsr")
    for part in (sub.normal, sub.abnormal):
        assert part.inc_op.shape[-1] == 0
        assert part.cov_bits.shape[-1] == 0
        assert part.inc_indptr_op.shape[-1] == 0
        assert part.inv_tracelen.shape[-1] == 0
        assert part.pc_trace.shape[-1] > 0
        assert part.ss_child.shape[-1] > 0  # call edges still needed


def test_pcsr_batched_blob_and_sharded(graphs):
    """Stacked vmap, blob staging and the 2D-mesh sharded dispatch all
    reproduce the single-device pcsr ranking."""
    from microrank_tpu.parallel.mesh import (
        SHARD_AXIS,
        WINDOW_AXIS,
        make_mesh,
    )
    from microrank_tpu.parallel.sharded_rank import (
        rank_windows_batched,
        rank_windows_sharded,
        stack_window_graphs,
        stage_sharded,
    )
    from microrank_tpu.rank_backends.blob import stage_rank_window

    g0, _, names = graphs
    base, base_scores = _ranked(g0, names, "pcsr")

    stacked = stack_window_graphs([device_subset(g0, "pcsr")] * 2)
    ti, ts, nv = jax.device_get(
        rank_windows_batched(stacked, CFG.pagerank, CFG.spectrum, "pcsr")
    )
    for b in range(2):
        assert [names[int(i)] for i in ti[b][: int(nv[b])]] == base

    out = jax.device_get(
        stage_rank_window(
            device_subset(g0, "pcsr"),
            CFG.pagerank,
            CFG.spectrum,
            "pcsr",
            blob=True,
            conv_trace=True,
        )
    )
    assert [names[int(i)] for i in out[0][: int(out[2])]] == base
    assert int(out[4]) == CFG.pagerank.iterations  # conv trace rode along

    if len(jax.devices()) >= 4:
        mesh = make_mesh((2, 2), (WINDOW_AXIS, SHARD_AXIS))
        batched = stage_sharded([g0, g0], mesh, "pcsr")
        # stage_sharded's recipe tiles the trace axis exactly.
        assert (
            batched.normal.pc_trace.shape[-2] * PCSR_PART_TRACES
            == batched.normal.kind.shape[-1]
        )
        ti, ts, nv = jax.device_get(
            rank_windows_sharded(
                batched, CFG.pagerank, CFG.spectrum, mesh, "pcsr"
            )
        )
        for b in range(2):
            n = int(nv[b])
            assert [names[int(i)] for i in ti[b][:n]] == base
            np.testing.assert_allclose(
                np.asarray(ts[b][:n], np.float64),
                base_scores,
                rtol=2e-5,
                atol=1e-5,
            )


def test_sharded_pcsr_rejects_untiled_stack(graphs):
    """A stack without the pcsr trace alignment must be rejected loudly,
    not silently mis-slab."""
    from microrank_tpu.parallel.mesh import (
        SHARD_AXIS,
        WINDOW_AXIS,
        make_mesh,
    )
    from microrank_tpu.parallel.sharded_rank import (
        rank_windows_sharded,
        stack_window_graphs,
    )

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 host devices")
    g0, _, _ = graphs
    mesh = make_mesh((2, 2), (WINDOW_AXIS, SHARD_AXIS))
    stacked = stack_window_graphs(
        [device_subset(g0, "pcsr")] * 2, shard_multiple=2
    )
    with pytest.raises(ValueError, match="tiled"):
        rank_windows_sharded(
            jax.device_put(stacked), CFG.pagerank, CFG.spectrum, mesh,
            "pcsr",
        )


def test_resolve_shard_kernel_prefers_pcsr_past_budget(graphs):
    """Past the per-shard packed budget, the shared shard-kernel policy
    lands on pcsr (the memory-bounded fallback) when the views exist."""
    import dataclasses

    from microrank_tpu.config import RuntimeConfig
    from microrank_tpu.parallel.mesh import (
        SHARD_AXIS,
        WINDOW_AXIS,
        make_mesh,
    )
    from microrank_tpu.parallel.sharded_rank import resolve_shard_kernel

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 host devices")
    g0, _, _ = graphs
    mesh = make_mesh((2, 2), (WINDOW_AXIS, SHARD_AXIS))
    rt = dataclasses.replace(RuntimeConfig(), dense_budget_bytes=64)
    assert resolve_shard_kernel([g0], mesh, rt) == "pcsr"


def test_pcsr_convergence_trace_and_all_methods(graphs):
    """The telemetry twins run on pcsr: the residual-traced program and
    the all-methods program both dispatch and agree on top-1."""
    from microrank_tpu.rank_backends.jax_tpu import (
        rank_window_all_methods_device,
        rank_window_traced_device,
    )

    g0, _, names = graphs
    base, _ = _ranked(g0, names, "pcsr")
    ti, ts, nv, res, n_it = jax.device_get(
        rank_window_traced_device(
            g0, CFG.pagerank, CFG.spectrum, None, "pcsr"
        )
    )
    assert [names[int(i)] for i in ti[: int(nv)]] == base
    assert int(n_it) == CFG.pagerank.iterations
    assert np.all(np.isfinite(res))
    mi, ms, mv = jax.device_get(
        rank_window_all_methods_device(
            g0, CFG.pagerank, CFG.spectrum, None, "pcsr"
        )
    )
    assert mi.shape[0] > 1 and int(mv) > 0
