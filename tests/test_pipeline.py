"""Pipeline + CLI integration: window loop, sinks, checkpoint, compat."""

import json

import numpy as np
import pandas as pd
import pytest

from microrank_tpu.config import CompatConfig, MicroRankConfig
from microrank_tpu.pipeline import (
    OnlineRCA,
    WindowCursor,
    load_slo,
    run_rca,
    save_slo,
)
from microrank_tpu.detect import compute_slo
from microrank_tpu.testing import SyntheticConfig, generate_case


@pytest.fixture(scope="module")
def case():
    return generate_case(
        SyntheticConfig(
            n_operations=24, n_traces=200, seed=9, n_kinds=24,
            child_keep_prob=0.6,
        )
    )


def test_run_rca_end_to_end(case, tmp_path):
    results = run_rca(
        case.normal, case.abnormal, MicroRankConfig(), out_dir=tmp_path
    )
    anomalous = [r for r in results if r.anomaly and r.ranking]
    assert anomalous, "no anomalous window found"
    top1 = anomalous[0].ranking[0][0]
    assert top1 == case.fault_pod_op
    # Sink artifacts.
    lines = (tmp_path / "windows.jsonl").read_text().strip().splitlines()
    assert len(lines) == len(results)
    rec = json.loads(lines[0])
    assert rec["anomaly"] and rec["ranking"][0][0] == case.fault_pod_op
    csv = pd.read_csv(tmp_path / "result.csv")
    assert list(csv.columns) == [
        "level", "result", "rank", "confidence", "window_start",
    ]
    assert csv.iloc[0]["result"] == case.fault_pod_op
    # Timings recorded for the anomalous window.
    assert "rank" in anomalous[0].timings
    # Cursor cleared after a clean run.
    assert not (tmp_path / "cursor.json").exists()


def test_reference_compat_overwrite_csv(case, tmp_path):
    cfg = MicroRankConfig.reference_compat()
    results = run_rca(case.normal, case.abnormal, cfg, out_dir=tmp_path)
    assert any(r.anomaly for r in results)
    csv = pd.read_csv(tmp_path / "result.csv")
    # Reference-exact 4-column shape (online_rca.py:212).
    assert list(csv.columns) == ["level", "result", "rank", "confidence"]


def test_partition_swap_changes_ranking(case, tmp_path):
    plain = run_rca(case.normal, case.abnormal, MicroRankConfig())
    cfg = MicroRankConfig(compat=CompatConfig(partition_swap=True))
    swapped = run_rca(case.normal, case.abnormal, cfg)
    r_plain = next(r for r in plain if r.ranking)
    r_swap = next(r for r in swapped if r.ranking)
    assert r_plain.ranking[0][0] != r_swap.ranking[0][0]


def test_slo_cache_roundtrip(case, tmp_path):
    vocab, baseline = compute_slo(case.normal)
    path = tmp_path / "slo.npz"
    save_slo(path, vocab, baseline)
    vocab2, baseline2 = load_slo(path)
    assert vocab2.names == vocab.names
    np.testing.assert_array_equal(baseline2.mean_ms, baseline.mean_ms)
    np.testing.assert_array_equal(baseline2.std_ms, baseline.std_ms)

    rca = OnlineRCA(MicroRankConfig())
    rca.fit_baseline(case.normal, cache_path=path)  # loads, not recomputes
    assert rca.slo_vocab.names == vocab.names


def test_window_cursor(tmp_path):
    cur = WindowCursor(tmp_path / "cursor.json")
    assert cur.load() is None
    cur.save("2025-02-14 12:05:00")
    assert cur.load() == "2025-02-14 12:05:00"
    cur.clear()
    assert cur.load() is None


def test_resume_skips_processed_windows(case, tmp_path):
    cfg = MicroRankConfig()
    rca = OnlineRCA(cfg)
    rca.fit_baseline(case.normal)
    # Pretend a prior run stopped after the first window.
    first = rca.run(case.abnormal, out_dir=tmp_path)
    assert len(first) >= 1
    cursor = WindowCursor(tmp_path / "cursor.json")
    end_of_first = pd.Timestamp(first[0].end)
    skip = pd.Timedelta(minutes=cfg.window.skip_minutes)
    cursor.save(str(end_of_first + (skip if first[0].ranking else pd.Timedelta(0))))
    resumed = rca.run(case.abnormal, out_dir=tmp_path, resume=True)
    assert len(resumed) == len(first) - 1


def test_empty_window_skipped(case):
    # An empty dump -> zero windows, no crash (the reference's bare
    # ``return False`` would crash the unpack at online_rca.py:167).
    rca = OnlineRCA(MicroRankConfig())
    rca.fit_baseline(case.normal)
    assert rca.run(case.abnormal.iloc[0:0]) == []


def test_cli_synth_and_run(tmp_path):
    from microrank_tpu.cli import main

    data = tmp_path / "data"
    rc = main(
        [
            "synth", "-o", str(data), "--operations", "16", "--traces", "120",
            "--seed", "3", "--kinds", "24",
        ]
    )
    assert rc == 0
    truth = json.loads((data / "ground_truth.json").read_text())
    out = tmp_path / "out"
    rc = main(
        [
            "run",
            "--normal", str(data / "normal" / "traces.csv"),
            "--abnormal", str(data / "abnormal" / "traces.csv"),
            "-o", str(out),
            "--backend", "jax",
        ]
    )
    assert rc == 0
    csv = pd.read_csv(out / "result.csv")
    assert csv.iloc[0]["result"] == truth["fault_pod_op"]


def test_cli_numpy_backend_agrees(tmp_path):
    from microrank_tpu.cli import main

    data = tmp_path / "data"
    main(["synth", "-o", str(data), "--operations", "12", "--traces", "80",
          "--seed", "4"])
    outs = {}
    for backend in ("jax", "numpy_ref"):
        out = tmp_path / backend
        main(
            ["run", "--normal", str(data / "normal" / "traces.csv"),
             "--abnormal", str(data / "abnormal" / "traces.csv"),
             "-o", str(out), "--backend", backend]
        )
        if (out / "result.csv").exists():
            outs[backend] = pd.read_csv(out / "result.csv")
    if len(outs) == 2:
        assert outs["jax"].iloc[0]["result"] == outs["numpy_ref"].iloc[0]["result"]


def test_batched_windows_match_sequential(case, tmp_path):
    # Three anomalous windows (the same case tiled at +10/+20 min);
    # batch_windows=True must produce identical rankings to sequential.
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.pipeline import TableRCA

    tiles = []
    for k in range(3):
        df = case.abnormal.copy()
        off = pd.Timedelta(minutes=10 * k)
        df["startTime"] = df["startTime"] + off
        df["endTime"] = df["endTime"] + off
        df["traceID"] = df["traceID"] + f"-w{k}"
        df["spanID"] = df["spanID"] + f"-w{k}"
        df["ParentSpanId"] = df["ParentSpanId"].where(
            df["ParentSpanId"] == "", df["ParentSpanId"] + f"-w{k}"
        )
        tiles.append(df)
    multi = pd.concat(tiles, ignore_index=True)
    case.normal.to_csv(tmp_path / "n.csv", index=False)
    multi.to_csv(tmp_path / "a.csv", index=False)

    cfg = MicroRankConfig()
    rca = TableRCA(cfg)
    rca.fit_baseline(native.load_span_table(tmp_path / "n.csv"))
    table = native.load_span_table(tmp_path / "a.csv")
    seq = rca.run(table)
    bat = rca.run(table, batch_windows=True)
    assert len(seq) == len(bat)
    n_ranked = sum(1 for r in seq if r.ranking)
    assert n_ranked >= 2
    for a, b in zip(seq, bat):
        assert (a.start, a.anomaly, a.skipped_reason) == (
            b.start, b.anomaly, b.skipped_reason,
        )
        assert [n for n, _ in a.ranking] == [n for n, _ in b.ranking]
        np.testing.assert_allclose(
            [s for _, s in a.ranking], [s for _, s in b.ranking], rtol=1e-4
        )


def test_table_lane_pipelined_matches_sync(case, tmp_path):
    """pipeline_depth=2 (async overlap) == depth=1, incl. sink order."""
    from dataclasses import replace

    from microrank_tpu.native import native_available
    from microrank_tpu.pipeline import run_rca_native

    if not native_available():
        pytest.skip("native lane unavailable")
    case.normal.to_csv(tmp_path / "normal.csv", index=False)
    case.abnormal.to_csv(tmp_path / "abnormal.csv", index=False)
    cfg = MicroRankConfig()
    outs = {}
    for depth in (1, 2, 4):
        c = replace(cfg, runtime=replace(cfg.runtime, pipeline_depth=depth))
        out = tmp_path / f"out{depth}"
        outs[depth] = (
            run_rca_native(
                tmp_path / "normal.csv", tmp_path / "abnormal.csv", c, out
            ),
            (out / "windows.jsonl").read_text().splitlines(),
        )
    r1, lines1 = outs[1]
    for depth in (2, 4):
        rd, lines_d = outs[depth]
        assert len(rd) == len(r1)
        for a, b in zip(r1, rd):
            assert a.ranking == b.ranking
            assert (a.start, a.anomaly, a.skipped_reason) == (
                b.start, b.anomaly, b.skipped_reason
            )
        # sink emission preserved window order and count
        starts1 = [json.loads(l)["start"] for l in lines1]
        starts_d = [json.loads(l)["start"] for l in lines_d]
        assert starts1 == starts_d


def test_timeline_window_loop_and_skip(tmp_path):
    # A continuous multi-window stream drives the real sliding-window
    # orchestration: faulted windows are detected and ranked to the
    # injected fault; an anomalous window advances the cursor by
    # detect+skip (reference online_rca.py:215-216), so the clean window
    # immediately after a faulted one is jumped over.
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(
            n_operations=20, n_traces=120, seed=21, n_kinds=24,
            child_keep_prob=0.6,
        ),
        6,
        [1, 4],
    )
    cfg = MicroRankConfig()
    results = run_rca(tl.normal, tl.timeline, cfg, out_dir=tmp_path)
    ranked = [r for r in results if r.anomaly and r.ranking]
    assert ranked, "no anomalous window ranked"
    for r in ranked:
        assert r.ranking[0][0] == tl.fault_pod_op
    # Window starts visited: faulted windows trigger the +skip jump, so
    # fewer windows are visited than exist in the stream.
    assert len(results) < 6
    # Ranked window starts align to the faulted windows' bounds.
    # The loop's windows stride from the first trace, not the generator's
    # grid — ranked windows must OVERLAP a faulted window's interval.
    faulted_spans = [
        (
            tl.start + pd.Timedelta(minutes=5 * w),
            tl.start + pd.Timedelta(minutes=5 * (w + 1)),
        )
        for w in (1, 4)
    ]
    for r in ranked:
        w0 = pd.Timestamp(r.start)
        w1 = w0 + pd.Timedelta(minutes=5)
        assert any(w0 < f1 and f0 < w1 for f0, f1 in faulted_spans), r.start


def test_cli_config_json_roundtrip(tmp_path):
    # A full MicroRankConfig serialized to JSON drives the CLI: to_dict ->
    # file -> from_dict inside _config_from_args, overriding every flag.
    from microrank_tpu.cli.main import main as cli_main
    from microrank_tpu.config import PageRankConfig, SpectrumConfig
    from microrank_tpu.testing import SyntheticConfig, generate_case

    cfg = MicroRankConfig(
        pagerank=PageRankConfig(iterations=30, damping=0.9),
        spectrum=SpectrumConfig(method="ochiai", top_max=7),
    )
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg.to_dict()))

    case = generate_case(
        SyntheticConfig(n_operations=16, n_traces=100, seed=4,
                        n_kinds=24, child_keep_prob=0.6)
    )
    (tmp_path / "d").mkdir()
    case.normal.to_csv(tmp_path / "d" / "normal.csv", index=False)
    case.abnormal.to_csv(tmp_path / "d" / "abnormal.csv", index=False)
    rc = cli_main(
        ["run", "--normal", str(tmp_path / "d" / "normal.csv"),
         "--abnormal", str(tmp_path / "d" / "abnormal.csv"),
         "-o", str(tmp_path / "out"),
         "--config-json", str(cfg_path)]
    )
    assert rc == 0
    records = [
        json.loads(line)
        for line in (tmp_path / "out" / "windows.jsonl").read_text().splitlines()
    ]
    ranked = [r for r in records if r["ranking"]]
    assert ranked
    # top_max=7 -> top_max + 6 = 13 rows requested; vocab is smaller here,
    # so every valid op is ranked (more than the default 11 only if vocab
    # allows) — just assert the config actually took effect via ochiai's
    # bounded scores (dstar2 produces values >> 1).
    assert all(s <= 1.5 for _, s in ranked[0]["ranking"])


def test_trace_context(tmp_path):
    # jax.profiler trace wrapper: produces a dump dir when given one and
    # is a no-op without.
    import jax.numpy as jnp

    from microrank_tpu.utils.profiling import trace_context

    with trace_context(None):
        pass
    d = tmp_path / "trace"
    with trace_context(str(d)):
        jnp.arange(8).sum().block_until_ready()
    assert d.exists() and any(d.rglob("*"))


def test_table_rca_resume(tmp_path):
    # The native fast lane mirrors OnlineRCA's window-cursor resume: a
    # saved cursor makes the next run skip already-emitted windows, and
    # a clean run clears it.
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.pipeline import TableRCA
    from microrank_tpu.pipeline.checkpoint import WindowCursor
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(n_operations=16, n_traces=80, seed=9), 3, [0, 2]
    )
    tl.normal.to_csv(tmp_path / "n.csv", index=False)
    tl.timeline.to_csv(tmp_path / "a.csv", index=False)
    normal = native.load_span_table(tmp_path / "n.csv")
    timeline = native.load_span_table(tmp_path / "a.csv")

    out1 = tmp_path / "run1"
    rca = TableRCA(MicroRankConfig())
    rca.fit_baseline(normal)
    first = rca.run(timeline, out_dir=out1)
    assert len(first) >= 2
    # Clean completion clears the cursor.
    assert WindowCursor(out1 / "cursor.json").load() is None

    # Pretend a prior run stopped after the first window: save the
    # cursor a full run would have written at that point.
    cfg = MicroRankConfig()
    skip_min = cfg.window.skip_minutes if first[0].ranking else 0.0
    resume_at = (
        pd.Timestamp(first[0].end) + pd.Timedelta(minutes=skip_min)
    )
    out2 = tmp_path / "run2"
    out2.mkdir()
    WindowCursor(out2 / "cursor.json").save(str(resume_at))
    resumed = rca.run(timeline, out_dir=out2, resume=True)
    assert len(resumed) == len(first) - 1
    assert [r.start for r in resumed] == [r.start for r in first[1:]]


def test_cli_mesh_flag(tmp_path):
    # --mesh routes the run through the sharded TableRCA path (and
    # --kernel through the kernel config) without a config-json file;
    # a 2x4 mesh auto-enables batch-mode ranking.
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.cli import main
    from microrank_tpu.cli.main import _parse_mesh

    assert _parse_mesh(None) is None
    assert _parse_mesh("8") == (8,)
    assert _parse_mesh("2x4") == (2, 4)
    with pytest.raises(SystemExit):
        _parse_mesh("0x4")
    with pytest.raises(SystemExit):
        _parse_mesh("abc")

    data = tmp_path / "data"
    rc = main(
        [
            "synth", "-o", str(data), "--operations", "16", "--traces",
            "120", "--seed", "3", "--kinds", "24",
        ]
    )
    assert rc == 0
    truth = json.loads((data / "ground_truth.json").read_text())
    for mesh in ("8", "2x4"):
        out = tmp_path / f"out_{mesh}"
        rc = main(
            [
                "run",
                "--engine", "native",
                "--normal", str(data / "normal" / "traces.csv"),
                "--abnormal", str(data / "abnormal" / "traces.csv"),
                "-o", str(out),
                "--mesh", mesh,
                "--kernel", "csr",
            ]
        )
        assert rc == 0, mesh
        csv = pd.read_csv(out / "result.csv")
        assert csv.iloc[0]["result"] == truth["fault_pod_op"], mesh

    # The pandas pipeline has no sharded path: --mesh there is a clear
    # error, not a silently unsharded run.
    rc = main(
        [
            "run",
            "--engine", "pandas",
            "--normal", str(data / "normal" / "traces.csv"),
            "--abnormal", str(data / "abnormal" / "traces.csv"),
            "-o", str(tmp_path / "out_pandas"),
            "--mesh", "8",
        ]
    )
    assert rc == 2


def test_cli_profile_dir(tmp_path):
    # --profile-dir wraps the window loop in a jax.profiler trace and
    # leaves a Perfetto dump behind.
    from microrank_tpu.cli import main

    data = tmp_path / "data"
    assert main(
        [
            "synth", "-o", str(data), "--operations", "12", "--traces",
            "80", "--seed", "2",
        ]
    ) == 0
    prof = tmp_path / "prof"
    rc = main(
        [
            "run",
            "--normal", str(data / "normal" / "traces.csv"),
            "--abnormal", str(data / "abnormal" / "traces.csv"),
            "-o", str(tmp_path / "out"),
            "--profile-dir", str(prof),
        ]
    )
    assert rc == 0
    assert any(prof.rglob("*"))  # the trace dump exists


def test_table_lane_async_dispatch_matches_sync(case, tmp_path):
    """async_dispatch=True (stage/fetch worker threads) must produce the
    same rankings, order, and sink lines as the synchronous loop."""
    from dataclasses import replace

    from microrank_tpu.native import native_available
    from microrank_tpu.pipeline import run_rca_native

    if not native_available():
        pytest.skip("native lane unavailable")
    case.normal.to_csv(tmp_path / "normal.csv", index=False)
    case.abnormal.to_csv(tmp_path / "abnormal.csv", index=False)
    cfg = MicroRankConfig()
    outs = {}
    for mode in (False, True):
        c = replace(
            cfg,
            runtime=replace(
                cfg.runtime, async_dispatch=mode, pipeline_depth=2
            ),
        )
        out = tmp_path / f"out_async{mode}"
        outs[mode] = (
            run_rca_native(
                tmp_path / "normal.csv", tmp_path / "abnormal.csv", c, out
            ),
            (out / "windows.jsonl").read_text().splitlines(),
        )
    r_sync, lines_sync = outs[False]
    r_async, lines_async = outs[True]
    assert len(r_async) == len(r_sync) > 0
    for a, b in zip(r_sync, r_async):
        assert a.ranking == b.ranking
        assert (a.start, a.anomaly, a.skipped_reason) == (
            b.start, b.anomaly, b.skipped_reason
        )
    assert len(lines_async) == len(lines_sync)


def test_table_lane_bulk_fetch_matches_stream(case, tmp_path):
    """fetch_mode='bulk' (batched deferred fetches) must produce the same
    rankings, order, and sink lines as streaming, for both sync and
    async dispatch and for a bulk chunk smaller than the window count
    (forces a mid-loop flush)."""
    from dataclasses import replace

    from microrank_tpu.native import native_available
    from microrank_tpu.pipeline import run_rca_native

    if not native_available():
        pytest.skip("native lane unavailable")
    case.normal.to_csv(tmp_path / "normal.csv", index=False)
    case.abnormal.to_csv(tmp_path / "abnormal.csv", index=False)
    cfg = MicroRankConfig()
    outs = {}
    variants = {
        "stream": dict(fetch_mode="stream"),
        "bulk": dict(fetch_mode="bulk"),
        "bulk_chunk1": dict(fetch_mode="bulk", bulk_fetch_windows=1),
        "bulk_sync": dict(fetch_mode="bulk", async_dispatch=False),
    }
    for name, kw in variants.items():
        c = replace(cfg, runtime=replace(cfg.runtime, **kw))
        out = tmp_path / f"out_{name}"
        outs[name] = (
            run_rca_native(
                tmp_path / "normal.csv", tmp_path / "abnormal.csv", c, out
            ),
            (out / "windows.jsonl").read_text().splitlines(),
        )
    r_ref, lines_ref = outs["stream"]
    assert any(r.ranking for r in r_ref)

    def _sink_records(lines):
        # The PERSISTED content must match, not just the in-memory
        # results (which are mutated after emit): a flush that emitted
        # half-finished windows would show empty rankings here.
        import json as _json

        return [
            {
                k: rec.get(k)
                for k in ("start", "anomaly", "skipped_reason", "ranking")
            }
            for rec in map(_json.loads, lines)
        ]

    ref_records = _sink_records(lines_ref)
    assert any(rec["ranking"] for rec in ref_records)
    for name in ("bulk", "bulk_chunk1", "bulk_sync"):
        r, lines = outs[name]
        assert len(r) == len(r_ref), name
        for a, b in zip(r_ref, r):
            assert a.ranking == b.ranking, name
            assert (a.start, a.anomaly, a.skipped_reason) == (
                b.start, b.anomaly, b.skipped_reason
            ), name
        assert _sink_records(lines) == ref_records, name


def test_table_rca_resume_with_bulk_fetch(tmp_path):
    """Bulk fetch defers emission, so the cursor advances only at flush:
    a clean bulk run still clears the cursor, and resuming from a
    mid-run cursor skips exactly the emitted windows — no window is
    lost or double-ranked."""
    from dataclasses import replace

    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.pipeline import TableRCA
    from microrank_tpu.pipeline.checkpoint import WindowCursor
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(n_operations=16, n_traces=80, seed=9), 3, [0, 2]
    )
    tl.normal.to_csv(tmp_path / "n.csv", index=False)
    tl.timeline.to_csv(tmp_path / "a.csv", index=False)
    normal = native.load_span_table(tmp_path / "n.csv")
    timeline = native.load_span_table(tmp_path / "a.csv")

    cfg = MicroRankConfig()
    cfg_bulk = cfg.replace(
        runtime=replace(cfg.runtime, fetch_mode="bulk", bulk_fetch_windows=2)
    )
    rca = TableRCA(cfg_bulk)
    rca.fit_baseline(normal)

    out1 = tmp_path / "bulk1"
    first = rca.run(timeline, out_dir=out1)
    assert len(first) >= 2
    assert WindowCursor(out1 / "cursor.json").load() is None
    # Every anomalous window's ranking was PERSISTED (the r4 bulk-flush
    # bug emitted batch-mates half-finished).
    lines = [
        json.loads(l)
        for l in (out1 / "windows.jsonl").read_text().splitlines()
    ]
    for rec in lines:
        if rec["anomaly"] and not rec.get("skipped_reason"):
            assert rec["ranking"], rec["start"]

    # Resume mid-run: same cursor arithmetic as the stream-mode test.
    skip_min = cfg.window.skip_minutes if first[0].ranking else 0.0
    resume_at = (
        pd.Timestamp(first[0].end) + pd.Timedelta(minutes=skip_min)
    )
    out2 = tmp_path / "bulk2"
    out2.mkdir()
    WindowCursor(out2 / "cursor.json").save(str(resume_at))
    resumed = rca.run(timeline, out_dir=out2, resume=True)
    assert len(resumed) == len(first) - 1
    assert [r.start for r in resumed] == [r.start for r in first[1:]]
    assert [r.ranking for r in resumed] == [r.ranking for r in first[1:]]


@pytest.mark.parametrize(
    "chunk_n,fetch_mode,async_mode",
    [
        (2, "stream", True),   # partial final group (5 windows % 2)
        (3, "bulk", True),
        (4, "bulk", False),
        (8, "stream", False),  # one group larger than the window count
    ],
)
def test_chunked_dispatch_matches_per_window(
    tmp_path, chunk_n, fetch_mode, async_mode
):
    """dispatch_batch_windows > 1 (micro-batched dispatch: one stacked
    stage+rank per group) must reproduce the per-window rankings, emit
    to the sink in window order, and handle partial final groups —
    across stream/bulk x sync/async."""
    import dataclasses

    from microrank_tpu.config import RuntimeConfig, WindowConfig
    from microrank_tpu.native import load_span_table
    from microrank_tpu.pipeline.table_runner import TableRCA
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(n_operations=40, n_kinds=8, n_traces=120, seed=5),
        5,
        [0, 2, 3, 4],
    )
    tl.normal.to_csv(tmp_path / "normal.csv", index=False)
    tl.timeline.to_csv(tmp_path / "timeline.csv", index=False)
    normal = load_span_table(tmp_path / "normal.csv")
    timeline = load_span_table(tmp_path / "timeline.csv")

    def run(rt, out_dir=None):
        cfg = MicroRankConfig(
            window=WindowConfig(
                detect_minutes=tl.window_minutes, skip_minutes=0.0
            ),
            runtime=rt,
        )
        rca = TableRCA(cfg)
        rca.fit_baseline(normal)
        return rca.run(timeline, out_dir=out_dir)

    base = run(RuntimeConfig(dispatch_batch_windows=1))
    out = tmp_path / f"out_{chunk_n}_{fetch_mode}_{async_mode}"
    got = run(
        RuntimeConfig(
            dispatch_batch_windows=chunk_n,
            fetch_mode=fetch_mode,
            async_dispatch=async_mode,
        ),
        out_dir=out,
    )
    assert [r.start for r in got] == [r.start for r in base]
    assert [
        [n for n, _ in r.ranking] if r.ranking else None for r in got
    ] == [
        [n for n, _ in r.ranking] if r.ranking else None for r in base
    ]
    # Sink emission is per window, in window order, all rankings present.
    lines = [
        json.loads(l)
        for l in (out / "windows.jsonl").read_text().splitlines()
    ]
    assert [l["start"] for l in lines] == [r.start for r in got]
    for rec in lines:
        if rec["anomaly"] and not rec.get("skipped_reason"):
            assert rec["ranking"], rec["start"]
            assert "chunk_windows" in rec["timings"]


def test_chunked_dispatch_demotes_with_warning(tmp_path, caplog):
    """Conflicting modes (mesh / device_checks / batch_windows) demote
    dispatch_batch_windows to per-window dispatch WITH a warning."""
    import logging

    from microrank_tpu.config import RuntimeConfig, WindowConfig
    from microrank_tpu.native import load_span_table
    from microrank_tpu.pipeline.table_runner import TableRCA
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(n_operations=24, n_traces=80, seed=5), 2, [0, 1]
    )
    tl.normal.to_csv(tmp_path / "normal.csv", index=False)
    tl.timeline.to_csv(tmp_path / "timeline.csv", index=False)
    normal = load_span_table(tmp_path / "normal.csv")
    timeline = load_span_table(tmp_path / "timeline.csv")

    cfg = MicroRankConfig(
        window=WindowConfig(
            detect_minutes=tl.window_minutes, skip_minutes=0.0
        ),
        runtime=RuntimeConfig(
            dispatch_batch_windows=4, device_checks=True
        ),
    )
    rca = TableRCA(cfg)
    rca.fit_baseline(normal)
    with caplog.at_level(logging.WARNING):
        res = rca.run(timeline)
    assert any(
        "dispatch_batch_windows" in rec.message for rec in caplog.records
    )
    assert any(r.ranking for r in res)

    caplog.clear()
    cfg2 = MicroRankConfig(
        window=WindowConfig(
            detect_minutes=tl.window_minutes, skip_minutes=0.0
        ),
        runtime=RuntimeConfig(dispatch_batch_windows=4),
    )
    rca2 = TableRCA(cfg2)
    rca2.fit_baseline(normal)
    with caplog.at_level(logging.WARNING):
        rca2.run(timeline, batch_windows=True)
    assert any(
        "dispatch_batch_windows" in rec.message for rec in caplog.records
    )


def test_persistent_compile_cache_across_processes(tmp_path):
    """VERDICT r4 #3: a SECOND process compiling the same rank program
    hits the on-disk XLA compilation cache (MICRORANK_JIT_CACHE /
    _enable_jit_cache) — entries appear after process one and process
    two adds none (pure cache reads), with a visibly faster compile."""
    import os
    import subprocess
    import sys

    script = tmp_path / "compile_probe.py"
    # Self-contained probe: build one window, time the first jitted call.
    script.write_text(
        """
import json, time
from microrank_tpu.cli.main import _enable_jit_cache
_enable_jit_cache()
import jax
jax.config.update("jax_platforms", "cpu")
from microrank_tpu.config import MicroRankConfig
from microrank_tpu.detect import compute_slo, detect_numpy
from microrank_tpu.graph import build_detect_batch
from microrank_tpu.graph.build import build_window_graph
from microrank_tpu.rank_backends.jax_tpu import rank_window_device
from microrank_tpu.testing import SyntheticConfig, generate_case

cfg = MicroRankConfig()
case = generate_case(SyntheticConfig(n_operations=24, n_traces=120, seed=7))
vocab, baseline = compute_slo(case.normal)
batch, tids = build_detect_batch(case.abnormal, vocab)
det = detect_numpy(batch, baseline, cfg.detector)
abn = [t for t, a in zip(tids, det.abnormal) if a]
nrm = [t for t, a, v in zip(tids, det.abnormal, det.valid) if v and not a]
graph, _, _, _ = build_window_graph(case.abnormal, nrm, abn)
t0 = time.perf_counter()
out = jax.device_get(
    rank_window_device(graph, cfg.pagerank, cfg.spectrum, None, "packed")
)
print(json.dumps({"first_call_s": time.perf_counter() - t0}))
"""
    )
    from pathlib import Path

    cache = tmp_path / "jit_cache"
    repo_root = str(Path(__file__).resolve().parent.parent)
    env = {
        **os.environ,
        "MICRORANK_JIT_CACHE": str(cache),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo_root
        + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
    }

    def probe():
        res = subprocess.run(
            [sys.executable, str(script)],
            env=env,
            cwd="/root/repo",
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert res.returncode == 0, res.stderr[-2000:]
        return json.loads(res.stdout.strip().splitlines()[-1])

    cold = probe()
    entries_after_first = list(cache.rglob("*"))
    assert entries_after_first, "no cache entries persisted"
    warm = probe()
    entries_after_second = list(cache.rglob("*"))
    # Second process reads, not writes (same program, cache hit)...
    assert len(entries_after_second) == len(entries_after_first)
    # ...and compiles visibly faster than the cold process.
    assert warm["first_call_s"] < cold["first_call_s"] * 0.7, (cold, warm)
