"""Collector unit coverage (component C17): the pure parts — TOML event
manifests, case naming, optional-dependency gating — without a ClickHouse."""

import asyncio

import pytest

from microrank_tpu.collect.clickhouse import (
    ChaosEvent,
    collect_cases,
    load_events_toml,
)


def test_load_events_toml(tmp_path):
    p = tmp_path / "events.toml"
    p.write_text(
        """
[[chaos_events]]
timestamp = "2025-02-14 12:30:00"
namespace = "ts"
chaos_type = "latency"
service = "ts-order-service"

[[chaos_events]]
timestamp = "not-a-timestamp"
namespace = "ts"

[[chaos_events]]
timestamp = "2025-02-14 13:00:00"
namespace = "hipster"
service = "cartservice"
"""
    )
    events = load_events_toml(p)
    # The malformed-timestamp event is skipped with a warning.
    assert len(events) == 2
    assert events[0].namespace == "ts"
    assert events[0].case_name == "ts-order-service-0214-1230"
    assert events[1].case_name == "cartservice-0214-1300"


def test_collect_requires_clickhouse(tmp_path):
    pytest.importorskip  # noqa: B018 — only run when the dep is absent
    try:
        import clickhouse_connect  # noqa: F401

        pytest.skip("clickhouse_connect installed; gating not exercised")
    except ImportError:
        pass
    ev = [ChaosEvent(timestamp="2025-02-14 12:30:00", namespace="ts")]
    with pytest.raises(RuntimeError, match="clickhouse_connect"):
        asyncio.run(collect_cases(ev, "localhost", tmp_path))


def test_manifest_toml_roundtrip(tmp_path):
    import tomllib

    from microrank_tpu.collect.clickhouse import manifest_toml

    events = [
        ChaosEvent(
            timestamp="2025-02-14 12:30:00", namespace="ts",
            chaos_type="latency", service='svc"quoted"',
        )
    ]
    text = manifest_toml(events)
    data = tomllib.loads(text)
    assert data["chaos_injection"][0]["service"] == 'svc"quoted"'
    assert data["chaos_injection"][0]["case"].endswith("-0214-1230")
