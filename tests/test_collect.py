"""Collector unit coverage (component C17): the pure parts — TOML event
manifests, case naming, optional-dependency gating — without a ClickHouse."""

import asyncio

import pytest

from microrank_tpu.collect.clickhouse import (
    ChaosEvent,
    collect_cases,
    load_events_toml,
)


def test_load_events_toml(tmp_path):
    p = tmp_path / "events.toml"
    p.write_text(
        """
[[chaos_events]]
timestamp = "2025-02-14 12:30:00"
namespace = "ts"
chaos_type = "latency"
service = "ts-order-service"

[[chaos_events]]
timestamp = "not-a-timestamp"
namespace = "ts"

[[chaos_events]]
timestamp = "2025-02-14 13:00:00"
namespace = "hipster"
service = "cartservice"
"""
    )
    events = load_events_toml(p)
    # The malformed-timestamp event is skipped with a warning.
    assert len(events) == 2
    assert events[0].namespace == "ts"
    assert events[0].case_name == "ts-order-service-0214-1230"
    assert events[1].case_name == "cartservice-0214-1300"


def test_collect_requires_clickhouse(tmp_path):
    pytest.importorskip  # noqa: B018 — only run when the dep is absent
    try:
        import clickhouse_connect  # noqa: F401

        pytest.skip("clickhouse_connect installed; gating not exercised")
    except ImportError:
        pass
    ev = [ChaosEvent(timestamp="2025-02-14 12:30:00", namespace="ts")]
    with pytest.raises(RuntimeError, match="clickhouse_connect"):
        asyncio.run(collect_cases(ev, "localhost", tmp_path))


def test_manifest_toml_roundtrip(tmp_path):
    try:
        import tomllib
    except ModuleNotFoundError:  # 3.10: same API under the backport name
        import tomli as tomllib

    from microrank_tpu.collect.clickhouse import manifest_toml

    events = [
        ChaosEvent(
            timestamp="2025-02-14 12:30:00", namespace="ts",
            chaos_type="latency", service='svc"quoted"',
        )
    ]
    text = manifest_toml(events)
    data = tomllib.loads(text)
    assert data["chaos_injection"][0]["service"] == 'svc"quoted"'
    assert data["chaos_injection"][0]["case"].endswith("-0214-1230")


def test_interactive_events_scripted():
    from microrank_tpu.collect.clickhouse import interactive_events

    # One invalid timestamp (re-prompts), one full event, empty to stop —
    # the reference's interactive loop behavior (collect_data.py:145-172).
    answers = iter(
        [
            "not-a-timestamp",
            "2025-02-14 12:30:00",
            "ts",
            "latency",
            "cartsvc",
            "",
        ]
    )
    printed = []
    events = interactive_events(
        input_fn=lambda prompt: next(answers), print_fn=printed.append
    )
    assert len(events) == 1
    ev = events[0]
    assert (ev.timestamp, ev.namespace, ev.chaos_type, ev.service) == (
        "2025-02-14 12:30:00", "ts", "latency", "cartsvc",
    )
    assert any("Invalid timestamp" in p for p in printed)
    assert any("Stopping input" in p for p in printed)


def test_fetch_csv_retry_exhaustion_and_recovery(tmp_path):
    from microrank_tpu.collect.clickhouse import _fetch_csv

    class FlakyClient:
        """Fails the first ``fail_n`` raw_query calls, then succeeds."""

        def __init__(self, fail_n):
            self.fail_n = fail_n
            self.calls = 0

        async def raw_query(self, query, fmt):
            self.calls += 1
            if self.calls <= self.fail_n:
                raise ConnectionError(f"boom {self.calls}")
            return b"Timestamp,TraceId\n1,abc\n"

    sem = asyncio.Semaphore(2)

    # Recovery: 2 failures then success within retries=3.
    client = FlakyClient(fail_n=2)
    path = tmp_path / "ok.csv"
    ok = asyncio.run(_fetch_csv(client, "SELECT 1", path, sem))
    assert ok is True
    assert client.calls == 3
    assert path.read_bytes().startswith(b"Timestamp")

    # Exhaustion: every attempt fails -> False, no file, exactly
    # ``retries`` attempts.
    client = FlakyClient(fail_n=99)
    path = tmp_path / "never.csv"
    ok = asyncio.run(_fetch_csv(client, "SELECT 1", path, sem))
    assert ok is False
    assert client.calls == 3
    assert not path.exists()
