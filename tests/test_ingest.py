"""Span admission + quarantine (ingest/): the hostile-data hardening.

Covers the admission ladder reason by reason, the dead-letter store's
exactly-once/bounded guarantees, the loader/tail-source satellites,
the baseline anti-poisoning gate, and the lanes (batch, serve, stream)
over the adversarial corpus fixtures under tests/data/hostile/ —
including the seeded chaos-registry acceptance run: all corruption
classes injected, zero crashes, the true culprit still top-1 tie-aware
on the clean subset, every rejected row in quarantine exactly once.
"""

import json
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from microrank_tpu.config import (
    ChaosConfig,
    IngestConfig,
    MicroRankConfig,
    ServeConfig,
    StreamConfig,
)
from microrank_tpu.ingest import (
    CORRUPTION_KINDS,
    QuarantineStore,
    TraceClock,
    admit_frame,
    admit_table,
    corrupt_frame,
    pre_admit_frame,
)
from microrank_tpu.testing import SyntheticConfig, generate_case

HOSTILE = Path(__file__).parent / "data" / "hostile"


@pytest.fixture(scope="module")
def hostile_case():
    return generate_case(
        SyntheticConfig(n_operations=16, n_traces=60, seed=11)
    )


@pytest.fixture()
def store(tmp_path):
    return QuarantineStore(tmp_path / "quarantine.jsonl")


def _truth():
    return json.loads((HOSTILE / "TRUTH.json").read_text())


# ------------------------------------------------------------- ladder


def test_clean_frame_admits_unchanged(hostile_case, store):
    f = hostile_case.abnormal
    r = admit_frame(f, IngestConfig(), quarantine=store, source="t")
    assert r.n_rejected == 0
    assert r.n_admitted == r.n_input == len(f)
    assert r.admission_ratio == 1.0
    assert not r.degraded
    assert store.records == 0


def test_bad_timestamp_rejected_not_fatal(hostile_case, store):
    f = hostile_case.abnormal.copy()
    f["startTime"] = f["startTime"].astype(object)
    f.iloc[3, f.columns.get_loc("startTime")] = "garbage"
    r = admit_frame(f, IngestConfig(), quarantine=store)
    assert r.rejected == {"bad_timestamp": 1}
    assert r.n_admitted == len(f) - 1
    # Survivors' dtypes are coerced back to datetime64.
    assert pd.api.types.is_datetime64_any_dtype(r.frame["startTime"])


def test_bad_duration_and_overflow(hostile_case, store):
    cfg = IngestConfig(max_duration_us=10_000_000)
    f = hostile_case.abnormal.copy()
    f["duration"] = f["duration"].astype(object)
    f.iloc[0, f.columns.get_loc("duration")] = -5
    f.iloc[1, f.columns.get_loc("duration")] = "NaNish"
    f.iloc[2, f.columns.get_loc("duration")] = 10_000_001
    r = admit_frame(f, cfg, quarantine=store)
    assert r.rejected["bad_duration"] == 2
    assert r.rejected["duration_overflow"] == 1


def test_missing_id_rejected(hostile_case, store):
    f = hostile_case.abnormal.copy()
    f.iloc[0, f.columns.get_loc("spanID")] = ""
    f.iloc[1, f.columns.get_loc("traceID")] = None
    r = admit_frame(f, IngestConfig(), quarantine=store)
    assert r.rejected == {"missing_id": 2}


def test_dup_span_keeps_first(hostile_case, store):
    f = corrupt_frame(hostile_case.abnormal, "dup_span", seed=1)
    n_dups = len(f) - len(hostile_case.abnormal)
    r = admit_frame(f, IngestConfig(), quarantine=store)
    assert r.rejected == {"dup_span": n_dups}
    assert r.n_admitted == len(hostile_case.abnormal)
    # The clean subset has unique (traceID, spanID) keys.
    assert not r.frame[["traceID", "spanID"]].duplicated().any()


def test_orphan_stitched_by_default(hostile_case, store):
    f = corrupt_frame(hostile_case.abnormal, "orphan", seed=2)
    r = admit_frame(f, IngestConfig(), quarantine=store)
    assert r.n_rejected == 0
    assert r.stitched_orphans > 0
    # Stitched spans became roots: their parent link is cleared.
    parent = r.frame["ParentSpanId"].fillna("").astype(str)
    assert not parent.str.startswith("ghost-").any()


def test_orphan_drop_policy(hostile_case, store):
    f = corrupt_frame(hostile_case.abnormal, "orphan", seed=2)
    r = admit_frame(
        f, IngestConfig(orphan_policy="drop"), quarantine=store
    )
    assert r.rejected.get("orphan", 0) > 0
    assert r.stitched_orphans == 0


def test_clock_skew_clamped_and_hopeless(hostile_case, store):
    f = hostile_case.abnormal
    w0 = f["startTime"].min().floor("min")
    w1 = w0 + pd.Timedelta(minutes=5)
    dirty = f.copy()
    # One span 10 minutes ahead (clampable), one 3 days back (hopeless).
    st = dirty["startTime"].copy()
    st.iloc[0] = st.iloc[0] + pd.Timedelta(minutes=10)
    st.iloc[1] = st.iloc[1] - pd.Timedelta(days=3)
    dirty["startTime"] = st
    r = admit_frame(
        dirty, IngestConfig(), quarantine=store,
        window_bounds=(w0, w1),
    )
    assert r.rejected == {"clock_skew": 1}
    assert r.clamped_skew == 1
    hi = pd.Timestamp(w1) + pd.Timedelta(seconds=300)
    assert (r.frame["startTime"] <= hi).all()


def test_trace_length_budget(hostile_case, store):
    cfg = IngestConfig(max_spans_per_trace=5)
    r = admit_frame(hostile_case.abnormal, cfg, quarantine=store)
    assert r.rejected.get("trace_too_long", 0) > 0
    assert (
        r.frame.groupby("traceID")["spanID"].count() <= 5
    ).all()


def test_vocab_growth_guard_kills_bomb(hostile_case, store):
    from microrank_tpu.io.naming import operation_names

    known = frozenset(
        operation_names(hostile_case.normal, "service").unique()
    )
    f = corrupt_frame(
        hostile_case.abnormal, "cardinality_bomb", seed=3,
        bomb_ops=48,
    )
    r = admit_frame(
        f, IngestConfig(max_new_ops_per_window=32),
        quarantine=store, known_ops=known,
    )
    # Past the growth cap, EVERY never-seen-op span rejects: no bomb
    # op reaches the detector, the baseline, or the pad buckets.
    assert r.rejected.get("vocab_budget", 0) == 48
    assert not r.frame["operationName"].str.startswith("op-bomb").any()


def test_vocab_absolute_cap_keeps_heavy_ops(hostile_case, store):
    f = corrupt_frame(
        hostile_case.abnormal, "cardinality_bomb", seed=3,
        bomb_ops=48,
    )
    n_real = (
        hostile_case.abnormal["podName"].astype(str)
        + "_" + hostile_case.abnormal["operationName"].astype(str)
    ).nunique()
    r = admit_frame(
        f, IngestConfig(max_ops_per_window=n_real),
        quarantine=store,
    )
    # The thin bomb ops lose the span-count contest; real ops survive.
    assert r.rejected.get("vocab_budget", 0) > 0
    kept = (
        r.frame["podName"].astype(str)
        + "_" + r.frame["operationName"].astype(str)
    ).nunique()
    assert kept <= n_real
    assert r.window_ops <= n_real


def test_trace_clock_repairs_displaced_spans():
    # A trace's root span displaced +10min must clamp back toward the
    # trace's first-seen time (the torn-trace watermark/anomaly guard).
    t0 = pd.Timestamp("2025-01-01 12:00:00")
    frame = pd.DataFrame(
        {
            "traceID": ["t1"] * 3,
            "spanID": ["a", "b", "c"],
            "ParentSpanId": ["", "a", "a"],
            "operationName": ["op1", "op2", "op3"],
            "serviceName": ["s"] * 3,
            "podName": ["s-0"] * 3,
            "duration": [1000, 500, 500],
            "startTime": [t0, t0, t0 + pd.Timedelta(minutes=10)],
            "endTime": [
                t0 + pd.Timedelta(milliseconds=1),
                t0 + pd.Timedelta(milliseconds=1),
                t0 + pd.Timedelta(minutes=10),
            ],
        }
    )
    clock = TraceClock()
    clean, rejected = pre_admit_frame(
        frame, IngestConfig(), trace_clock=clock
    )
    assert not rejected
    bound = t0 + pd.Timedelta(seconds=30)
    assert (clean["startTime"] <= bound).all()


def test_trace_clock_is_bounded():
    clock = TraceClock(max_traces=4)
    t0 = pd.Timestamp("2025-01-01")
    for i in range(10):
        tr = np.array([f"t{i}"])
        start = pd.Series([t0 + pd.Timedelta(seconds=i)])
        clock.normalize(
            tr, start, None, np.array([True]), IngestConfig()
        )
    assert len(clock._first) <= 4


def test_admission_idempotent_on_fixtures(tmp_path):
    # Property: re-admitting the clean subset changes NOTHING — for
    # every corruption class fixture and the mixed file.
    from microrank_tpu.io import load_traces_csv

    cfg = IngestConfig(
        max_spans_per_trace=64, max_ops_per_window=64,
    )
    for name in [f"{k}.csv" for k in CORRUPTION_KINDS] + ["mixed.csv"]:
        store = QuarantineStore(tmp_path / f"{name}.jsonl")
        frame = load_traces_csv(HOSTILE / name, quarantine=store)
        r1 = admit_frame(frame, cfg, quarantine=store, source=name)
        r2 = admit_frame(r1.frame, cfg, quarantine=store, source=name)
        assert r2.n_rejected == 0, (name, r2.rejected)
        assert r2.clamped_skew == 0, name
        pd.testing.assert_frame_equal(r2.frame, r1.frame)


# --------------------------------------------------------- quarantine


def test_quarantine_exactly_once_with_reasons(tmp_path, hostile_case):
    store = QuarantineStore(tmp_path / "q.jsonl")
    f = corrupt_frame(hostile_case.abnormal, "dup_span", seed=5)
    r = admit_frame(f, IngestConfig(), quarantine=store)
    recs = [
        json.loads(line)
        for line in (tmp_path / "q.jsonl").read_text().splitlines()
    ]
    assert len(recs) == r.n_rejected == store.records
    assert all(rec["reason"] == "dup_span" for rec in recs)
    # Exactly once: no record repeats.
    keys = [json.dumps(rec["row"], sort_keys=True) for rec in recs]
    assert len(set(keys)) == len(keys)


def test_quarantine_bounded_drops_counted(tmp_path):
    store = QuarantineStore(tmp_path / "q.jsonl", max_bytes=400)
    for i in range(50):
        store.put_raw(f"line-{i},garbage", "unparseable_line", "t")
    assert store.dropped > 0
    assert (tmp_path / "q.jsonl").stat().st_size <= 400


def test_quarantine_unconfigured_counts_only(hostile_case):
    store = QuarantineStore(None)
    f = corrupt_frame(hostile_case.abnormal, "dup_span", seed=5)
    r = admit_frame(f, IngestConfig(), quarantine=store)
    assert store.records == r.n_rejected > 0


# ------------------------------------------------- loader (satellite)


def test_loader_one_poisoned_row_in_10k(tmp_path):
    # The satellite regression: a single poisoned row in a 10k-row CSV
    # no longer aborts the frame — it quarantines, the rest load.
    n = 10_000
    t0 = pd.Timestamp("2025-01-01 12:00:00")
    df = pd.DataFrame(
        {
            "traceID": [f"t{i // 4}" for i in range(n)],
            "spanID": [f"s{i}" for i in range(n)],
            "ParentSpanId": [""] * n,
            "operationName": ["op"] * n,
            "serviceName": ["svc"] * n,
            "podName": ["svc-0"] * n,
            "duration": [1000] * n,
            "startTime": [t0] * n,
            "endTime": [t0 + pd.Timedelta(seconds=1)] * n,
        }
    )
    df = df.astype({"startTime": object})
    df.iloc[4321, df.columns.get_loc("startTime")] = "NOT A TIME"
    path = tmp_path / "traces.csv"
    df.to_csv(path, index=False)
    from microrank_tpu.io import load_traces_csv

    store = QuarantineStore(tmp_path / "q.jsonl")
    out = load_traces_csv(path, quarantine=store)
    assert len(out) == n - 1
    assert store.records == 1
    rec = json.loads((tmp_path / "q.jsonl").read_text())
    assert rec["reason"] == "bad_timestamp"
    assert rec["row"]["spanID"] == "s4321"


# -------------------------------------------- tail source (satellite)


def test_tail_poison_line_dead_lettered_with_offset(tmp_path):
    # A line that never parses stops retrying after parse_retry_max
    # polls: it lands in the dead-letter store WITH its byte offset,
    # the cursor advances past it, and the stream keeps flowing.
    from microrank_tpu.ingest.quarantine import (
        configure_quarantine,
        get_quarantine,
    )
    from microrank_tpu.stream.sources import FileTailSource

    case = generate_case(
        SyntheticConfig(n_operations=8, n_traces=20, seed=2)
    )
    path = tmp_path / "grow.csv"
    case.normal.iloc[:40].to_csv(path, index=False)
    configure_quarantine(
        IngestConfig(), default_dir=tmp_path
    )
    src = FileTailSource(
        path, poll_seconds=0.0, idle_exit=3, sleep=lambda s: None,
        parse_retry_max=2,
    )
    it = iter(src)
    first = next(it)
    assert len(first) == 40
    # Append a poison line (wrong field count — never parses) plus a
    # good batch behind it.
    offset_before = path.stat().st_size
    # Too MANY fields: the CSV tokenizer raises on every whole-slice
    # parse (a too-short line would just pad with NaN and fall to the
    # loader's bad_timestamp path instead).
    poison = ",".join(f"x{i}" for i in range(30)) + "\n"
    with open(path, "a") as f:
        f.write(poison)
    good = case.normal.iloc[40:80]
    good.to_csv(path, mode="a", header=False, index=False)
    chunks = []
    for chunk in it:
        chunks.append(chunk)
        break
    got = sum(len(c) for c in chunks)
    assert got == len(good)
    store = get_quarantine()
    recs = [
        json.loads(line)
        for line in (tmp_path / "quarantine.jsonl")
        .read_text()
        .splitlines()
    ]
    assert len(recs) == 1
    assert recs[0]["reason"] == "unparseable_line"
    assert recs[0]["offset"] == offset_before
    assert "x29" in recs[0]["row"]["raw"]
    assert store.records == 1


# ------------------------------------- baseline guard (satellite)


def _stream_engine(tmp_path, cfg, source, normal):
    from microrank_tpu.stream import StreamEngine

    return StreamEngine(cfg, source, out_dir=tmp_path, normal_df=normal)


def test_corruption_burst_cannot_retrain_baseline_or_alarm(tmp_path):
    # A window whose admission ratio falls below min_admission_ratio
    # neither updates the online baseline nor opens (or resolves) an
    # incident — the SLO floor survives a corruption burst.
    from microrank_tpu.stream import StreamEngine
    from microrank_tpu.testing import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(n_operations=12, n_traces=60, seed=4), 4, []
    )
    # Duplicate-span burst in windows 1-2: 9 copies of every row, so
    # the windows' admission ratio collapses to ~0.1 — well below the
    # 0.5 refusal floor. (Duplicates pass the pre-windowing gate —
    # their timestamps are fine — so the WINDOW-level ladder is what
    # must refuse them.)
    frame = tl.timeline
    start = pd.to_datetime(frame["startTime"])
    w1 = tl.start + pd.Timedelta(minutes=5)
    w3 = tl.start + pd.Timedelta(minutes=15)
    burst = ((start >= w1) & (start < w3)).to_numpy()
    dups = frame[burst]
    frame = pd.concat([frame] + [dups] * 9, ignore_index=True)
    cfg = MicroRankConfig(
        stream=StreamConfig(
            window_minutes=5.0, allowed_lateness_seconds=2.0,
            checkpoint=False,
        ),
        ingest=IngestConfig(min_admission_ratio=0.5),
    )
    from microrank_tpu.stream.sources import ReplaySource

    engine = StreamEngine(
        cfg,
        ReplaySource(frame, chunk_spans=1000),
        out_dir=tmp_path,
        normal_df=tl.normal,
    )
    before = engine.baseline.n_updates
    m1_before = {
        k: v.m1 for k, v in engine.baseline._ops.items()
    }
    s = engine.run()
    skipped = [
        r for r in s.results if r.skipped_reason == "low_admission"
    ]
    assert skipped, [r.skipped_reason for r in s.results]
    assert s.incidents_opened == 0
    # The burst windows contributed NOTHING to the baseline: updates
    # advanced only for the clean windows.
    clean_windows = sum(
        1 for r in s.results
        if r.skipped_reason is None and not r.anomaly
    )
    assert engine.baseline.n_updates == before + clean_windows
    # And the SLO floor did not absorb garbage (garbage rows never
    # reached update at all — means moved only by healthy traffic).
    for k, m1 in engine.baseline._ops.items():
        assert np.isfinite(m1.m1)
    assert set(m1_before) == set(engine.baseline._ops)


# --------------------------------------------------- the three lanes


def test_batch_lane_over_mixed_fixture(tmp_path):
    from microrank_tpu.io import load_traces_csv
    from microrank_tpu.pipeline import OnlineRCA

    normal = load_traces_csv(HOSTILE / "normal.csv")
    mixed = load_traces_csv(HOSTILE / "mixed.csv")
    rca = OnlineRCA(MicroRankConfig())
    rca.fit_baseline(normal)
    results = rca.run(mixed, out_dir=tmp_path)
    assert results  # no crash is the headline
    ranked = [r for r in results if r.ranking]
    assert any(r.ingest_rejected > 0 for r in results)
    # Degraded-but-correct: corruption REMOVED real rows (their
    # information is genuinely gone from the clean subset), so exact
    # clean-run parity is not guaranteed — but the true culprit stays
    # at the top of every ranked window.
    truth = _truth()["fault_pod_op"]
    assert ranked
    for r in ranked:
        top3 = [n for n, _ in r.ranking[:3]]
        assert truth in top3, (r.start, top3)


@pytest.mark.parametrize("kind", list(CORRUPTION_KINDS))
def test_batch_lane_every_class_no_crash(kind, tmp_path):
    from microrank_tpu.io import load_traces_csv
    from microrank_tpu.pipeline import OnlineRCA

    normal = load_traces_csv(HOSTILE / "normal.csv")
    dirty = load_traces_csv(HOSTILE / f"{kind}.csv")
    rca = OnlineRCA(
        MicroRankConfig(
            ingest=IngestConfig(
                max_spans_per_trace=64, max_ops_per_window=64
            )
        )
    )
    rca.fit_baseline(normal)
    results = rca.run(dirty, out_dir=tmp_path)
    assert results


def test_serve_lane_degraded_and_422(tmp_path):
    from microrank_tpu.io import load_traces_csv
    from microrank_tpu.serve.protocol import AdmissionError, RankRequest
    from microrank_tpu.serve.server import ServeService

    normal = load_traces_csv(HOSTILE / "normal.csv")
    mixed = load_traces_csv(HOSTILE / "mixed.csv")
    cfg = MicroRankConfig(
        serve=ServeConfig(warmup=False, build_workers=0),
        ingest=IngestConfig(
            max_spans_per_trace=64, max_ops_per_window=64
        ),
    )
    svc = ServeService(cfg, out_dir=tmp_path)
    svc.fit_baseline(normal)
    svc.start()
    try:
        fut = svc.submit(
            RankRequest(
                request_id="hostile-1",
                spans=mixed.to_dict(orient="records"),
            )
        )
        res = fut.result(timeout=120)
        assert res.degraded_input and res.ingest_rejected > 0
        assert res.ranking, "salvageable payload must still rank"
        assert res.ranking[0][0] == _truth()["fault_pod_op"]
        # Unsalvageable: every timestamp is garbage -> 422.
        allbad = mixed.copy()
        allbad["startTime"] = "garbage"
        fut = svc.submit(
            RankRequest(
                request_id="hostile-2",
                spans=allbad.to_dict(orient="records"),
            )
        )
        with pytest.raises(AdmissionError) as exc:
            fut.result(timeout=120)
        assert exc.value.status == 422
        assert exc.value.rejected.get("bad_timestamp", 0) > 0
    finally:
        svc.shutdown()
    # The journal carries the admission evidence.
    events = [
        json.loads(line)
        for line in (tmp_path / "journal.jsonl").read_text().splitlines()
    ]
    assert any(e["event"] == "ingest" for e in events)


def test_stream_lane_over_mixed_fixture(tmp_path):
    from microrank_tpu.io import load_traces_csv
    from microrank_tpu.stream import StreamEngine
    from microrank_tpu.stream.sources import ReplaySource

    normal = load_traces_csv(HOSTILE / "normal.csv")
    mixed = load_traces_csv(HOSTILE / "mixed.csv")
    cfg = MicroRankConfig(
        stream=StreamConfig(
            window_minutes=5.0, allowed_lateness_seconds=2.0,
            checkpoint=False,
        ),
        ingest=IngestConfig(
            max_spans_per_trace=64, max_ops_per_window=64
        ),
    )
    engine = StreamEngine(
        cfg,
        ReplaySource(mixed, chunk_spans=500),
        out_dir=tmp_path,
        normal_df=normal,
    )
    s = engine.run()
    assert s.windows > 0
    ranked = [r for r in s.results if r.ranking]
    assert ranked and ranked[0].ranking[0][0] == _truth()["fault_pod_op"]


# ------------------------------------------------- native table lane


def _mini_table():
    from microrank_tpu.native import SpanTable

    n = 8
    return SpanTable(
        trace_id=np.array([0, 0, 0, 1, 1, 1, 1, 1], np.int32),
        svc_op=np.zeros(n, np.int32),
        pod_op=np.zeros(n, np.int32),
        duration_us=np.array(
            [100, -5, 100, 100, 100, 10**12, 100, 100], np.int64
        ),
        start_us=np.array(
            [10, 20, 30, 40, 50, 60, 70, 80], np.int64
        ),
        end_us=np.array(
            [100, 110, 20, 140, 150, 160, 170, 180], np.int64
        ),
        parent_row=np.array([-1, 0, 1, -1, 3, 4, 5, 6], np.int64),
        trace_names=["t0", "t1"],
        svc_op_names=["svc_op"],
        pod_op_names=["pod_op"],
        time_sorted=True,
    )


def test_admit_table_values_budgets_and_parent_remap(tmp_path):
    store = QuarantineStore(tmp_path / "q.jsonl")
    cfg = IngestConfig(
        max_duration_us=10**9, max_spans_per_trace=3
    )
    clean, rejected = admit_table(_mini_table(), cfg, quarantine=store)
    # Row 1 negative duration, row 2 inverted times, row 5 overflow,
    # and trace t1 (4 surviving spans) capped at 3 (one more rejected).
    assert rejected["bad_duration"] == 1
    assert rejected["bad_timestamp"] == 1
    assert rejected["duration_overflow"] == 1
    assert rejected["trace_too_long"] == 1
    assert clean.n_spans == 4
    # parent_row remapped: spans whose parent was rejected stitched to
    # roots (-1); survivors point at the parent's NEW position.
    assert clean.parent_row[0] == -1          # was root
    assert clean.parent_row.max() < clean.n_spans
    assert store.records == sum(rejected.values())


def test_admit_table_clean_passthrough():
    t = _mini_table()._replace(
        duration_us=np.full(8, 100, np.int64),
        end_us=np.full(8, 10**6, np.int64),
    )
    clean, rejected = admit_table(t, IngestConfig())
    assert rejected == {}
    assert clean is t


# ------------------------------------------------ chaos + acceptance


def _hostile_plan():
    return tuple(
        {
            "seam": "source_data", "kind": k, "after": i,
            "count": 1, "value": v,
        }
        for i, (k, v) in enumerate(
            [
                ("corrupt_row", 0.1), ("dup_span", 0.1),
                ("orphan", 0.1), ("clock_skew", 0.1),
                ("cardinality_bomb", 64),
            ]
        )
    )


def test_chaos_source_data_corruption_deterministic():
    from microrank_tpu.chaos import configure_chaos
    from microrank_tpu.stream.sources import ReplaySource

    case = generate_case(
        SyntheticConfig(n_operations=12, n_traces=40, seed=6)
    )
    cfg = MicroRankConfig(
        chaos=ChaosConfig(
            enabled=True, seed=9,
            faults=(
                {
                    "seam": "source_data", "kind": "corrupt_row",
                    "count": 1, "value": 0.2,
                },
            ),
        )
    )

    def run_once():
        configure_chaos(cfg)
        chunks = list(iter(ReplaySource(case.normal, chunk_spans=100)))
        configure_chaos(MicroRankConfig())
        return chunks[0]

    a, b = run_once(), run_once()
    pd.testing.assert_frame_equal(a, b)
    # The corruption actually fired: dtypes degraded to object.
    assert a["startTime"].dtype == object


def test_hostile_acceptance_stream(tmp_path):
    """The acceptance invariant: all corruption classes + cardinality
    bomb injected via the chaos registry; zero crashes across the run;
    the fault window ranks the true culprit top-1 tie-aware on the
    clean subset; every rejected row appears exactly once in the
    dead-letter store with a reason; vocab/pad budgets hold."""
    from microrank_tpu.stream import StreamEngine, SyntheticSource
    from microrank_tpu.utils.ranking_compare import (
        tie_aware_topk_agreement,
    )

    cfg = MicroRankConfig(
        chaos=ChaosConfig(enabled=True, seed=7, faults=_hostile_plan()),
        stream=StreamConfig(
            window_minutes=5.0, allowed_lateness_seconds=5.0,
            checkpoint=True,
        ),
    )
    src = SyntheticSource(
        n_windows=8, faulted=[4],
        synth_config=SyntheticConfig(
            n_operations=24, n_traces=150, seed=3
        ),
        chunk_spans=800,
    )
    engine = StreamEngine(
        cfg, src, out_dir=tmp_path, normal_df=src.normal
    )
    s = engine.run()
    assert s.windows == 8
    assert s.incidents_opened == 1 and s.incidents_resolved == 1
    ranked = [r for r in s.results if r.ranking]
    assert len(ranked) == 1 and ranked[0].anomaly
    names = [n for n, _ in ranked[0].ranking]
    scores = [v for _, v in ranked[0].ranking]
    ok, _ = tie_aware_topk_agreement(
        names, scores, [src.fault_pod_op], [scores[0]], k=1
    )
    assert ok and names[0] == src.fault_pod_op
    # Exactly once in the dead-letter store, every record reasoned.
    recs = [
        json.loads(line)
        for line in (tmp_path / "quarantine.jsonl")
        .read_text()
        .splitlines()
    ]
    assert recs
    reasons = {r["reason"] for r in recs}
    assert reasons <= {
        "bad_timestamp", "bad_duration", "dup_span", "clock_skew",
        "vocab_budget", "trace_too_long", "orphan", "missing_id",
        "duration_overflow",
    }
    keys = [json.dumps(r, sort_keys=True) for r in recs]
    assert len(set(keys)) == len(keys)
    # Counter/ledger agreement: the per-reason metric equals the store.
    from collections import Counter

    from microrank_tpu.obs import get_registry

    by_reason = Counter(r["reason"] for r in recs)
    metric = get_registry().get("microrank_ingest_rejected_total")
    counted = {
        s_["labels"]["reason"]: s_["value"] for s_ in metric.samples()
    }
    for reason, n in by_reason.items():
        assert counted.get(reason, 0) >= n
    # Budget guard observable: the bomb never grew the admitted vocab.
    gauge = get_registry().get("microrank_ingest_window_ops")
    assert gauge.samples()[0]["value"] <= 24 + 32
    # No bomb op was ever staged/ranked.
    for r in s.results:
        for name, _ in r.ranking or []:
            assert "bomb" not in name


def test_scenario_hostile_family_record():
    from microrank_tpu.scenarios import run_scenario
    from microrank_tpu.scenarios.spec import default_matrix

    spec = [
        s for s in default_matrix(0) if s.family == "hostile"
    ][0]
    rec = run_scenario(
        MicroRankConfig(), spec, stream_lane=True
    )
    assert rec["ingest_rejected"] > 0
    det = rec["detection"]
    assert det["tp"] == len(spec.faulted) and det["fp"] == 0
    stream = rec["stream"]
    assert stream["incidents_opened"] == 1
    f = rec["formulas"]["dstar2"]
    assert f["topk_rate"][3] == 1.0  # culprit top-3 on every window


def test_scenario_hostile_digest_deterministic():
    from microrank_tpu.scenarios.generate import (
        generate_scenario,
        workload_digest,
    )
    from microrank_tpu.scenarios.spec import default_matrix

    spec = [
        s for s in default_matrix(0) if s.family == "hostile"
    ][0]
    assert workload_digest(generate_scenario(spec)) == workload_digest(
        generate_scenario(spec)
    )


def test_config_round_trip_carries_ingest():
    cfg = MicroRankConfig(
        ingest=IngestConfig(
            orphan_policy="drop", max_ops_per_window=123
        )
    )
    back = MicroRankConfig.from_dict(cfg.to_dict())
    assert back.ingest.orphan_policy == "drop"
    assert back.ingest.max_ops_per_window == 123


# ----------------------------------------------------- fast-path ingest
def _payload_spans(n: int, ts_fmt) -> list:
    return [
        {
            "TraceId": f"t{i % 997}", "SpanId": f"s{i}",
            "ParentSpanId": "", "SpanName": f"op{i % 31}",
            "ServiceName": f"svc{i % 7}", "PodName": f"svc{i % 7}-pod0",
            "Duration": 1000 + i % 5000,
            "TraceStart": ts_fmt(i), "TraceEnd": ts_fmt(i),
        }
        for i in range(n)
    ]


def _legacy_frame(spans):
    """The pre-fast-path request parse, verbatim: row-wise DataFrame +
    per-element ``mixed`` timestamp inference — the parity oracle."""
    from microrank_tpu.io.schema import CLICKHOUSE_RENAME

    df = pd.DataFrame(spans).rename(columns=CLICKHOUSE_RENAME)
    df["startTime"] = pd.to_datetime(
        df["startTime"], format="mixed", errors="coerce"
    )
    df["endTime"] = pd.to_datetime(
        df["endTime"], format="mixed", errors="coerce"
    )
    return df


def test_frame_from_records_parity_with_legacy_parse():
    from microrank_tpu.io import frame_from_records

    iso = _payload_spans(
        200, lambda i: "2026-08-06T10:00:00.%06dZ" % (i * 7)
    )
    noniso = _payload_spans(
        200, lambda i: "06/08/2026 10:00:00.%06d" % (i * 7)
    )
    epoch = _payload_spans(
        200, lambda i: 1700000000000000 + i
    )
    malformed = list(iso)
    malformed[7] = dict(malformed[7], TraceStart="not-a-time")
    hetero = [
        {"traceID": "a", "startTime": "2026-08-06"},
        {"traceID": "b", "endTime": "2026-08-06"},
    ]
    for spans in (iso, noniso, epoch, malformed, hetero):
        pd.testing.assert_frame_equal(
            frame_from_records(spans), _legacy_frame(spans)
        )
    # NaT semantics survive: the malformed row coerces, not raises.
    assert frame_from_records(malformed)["startTime"].isna()[7]
    # Shapes the legacy path owns are declined, not mangled.
    assert frame_from_records([]) is None
    assert frame_from_records("nope") is None


def test_request_path_parse_ms_pinned_on_large_payload():
    """100k-span POST /rank payload parses in vectorized time.

    The legacy per-element ``mixed`` parse pays ~75 us/row of dateutil
    on non-ISO timestamps — ~15 s for this payload's two timestamp
    columns. The fast path (io.frame_from_records via spans_to_frame)
    guesses the format once and parses the whole column in C; the
    budget below has >3x headroom over the measured fast path while
    sitting far under the legacy cost, so a regression to row-wise
    parsing fails loudly.
    """
    import time as _time

    from microrank_tpu.serve.protocol import spans_to_frame

    spans = _payload_spans(
        100_000, lambda i: "06/08/2026 10:00:00.%06d" % (i % 1000000)
    )
    t0 = _time.perf_counter()
    df = spans_to_frame(spans)
    elapsed = _time.perf_counter() - t0
    assert len(df) == 100_000
    assert df["startTime"].notna().all()
    assert elapsed < 6.0, (
        f"request-path parse took {elapsed:.1f}s for 100k spans — "
        "the vectorized fast path regressed to row-wise parsing"
    )
