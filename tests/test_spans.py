"""Self-tracing pipeline (obs.spans / obs.flight / obs.profiler):
tracer semantics, trace-context propagation across the scheduler
thread, the build worker pool and the stream engine thread, the flight
recorder's dump triggers and formats, journal fsync durability, the
/profilez endpoint — and the DOGFOOD acceptance: with one pipeline
stage artificially slowed (injected sleep in the build pool), ``cli
run`` over the flight dump ranks that stage top-1.
"""

import json
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from microrank_tpu.config import (
    MicroRankConfig,
    ObsConfig,
    RuntimeConfig,
    ServeConfig,
    StreamConfig,
)
from microrank_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    SpanTracer,
    configure_tracer,
    get_registry,
    get_tracer,
    set_registry,
    set_tracer,
)
from microrank_tpu.testing import SyntheticConfig, generate_case


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture
def tracer_reset():
    """Engines install fresh process tracers; restore the default
    (disabled) one afterwards so tests stay isolated."""
    yield
    set_tracer(None)


def _stream_cfg(**obs_kw):
    return MicroRankConfig(
        stream=StreamConfig(
            window_minutes=5.0,
            allowed_lateness_seconds=5.0,
            pipeline_windows=3,
            build_workers=2,
        ),
        runtime=RuntimeConfig(prefer_bf16=False),
        obs=ObsConfig(flight_min_interval_seconds=0.0, **obs_kw),
    )


def _stream_source():
    from microrank_tpu.stream import SyntheticSource

    return SyntheticSource(
        8,
        [3, 4, 5],
        synth_config=SyntheticConfig(
            n_operations=20, n_kinds=16, n_traces=150, seed=5,
            window_minutes=5.0,
        ),
    )


# ----------------------------------------------------------------- tracer


def test_tracer_nesting_and_parent_links(tracer_reset):
    tr = SpanTracer(enabled=True)
    ctx = tr.new_trace("win-1")
    with tr.attach(ctx):
        with tr.span("detect") as detect_ctx:
            with tr.span("inner"):
                pass
    spans = {s.name: s for s in tr.snapshot()}
    assert set(spans) == {"detect", "inner"}
    assert spans["detect"].trace_id == "win-1"
    assert spans["detect"].parent_id == ctx.span_id
    assert spans["inner"].parent_id == detect_ctx.span_id
    assert spans["inner"].trace_id == "win-1"
    # context restored after the blocks
    assert tr.current_context() is ctx or tr.current_context() is None


def test_tracer_ring_bounded_and_counts_drops(tracer_reset):
    tr = SpanTracer(enabled=True, capacity=16)
    for i in range(50):
        with tr.span(f"s{i}"):
            pass
    assert len(tr) == 16
    assert tr.recorded == 50
    assert tr.dropped == 34
    # Oldest fell off: the ring holds the newest 16.
    assert [s.name for s in tr.snapshot()][0] == "s34"


def test_tracer_disabled_records_nothing(tracer_reset):
    tr = SpanTracer(enabled=False)
    with tr.span("detect"):
        pass
    tr.record_span(
        "window", ctx=tr.new_trace("t"), start_us=0, dur_us=1
    )
    assert len(tr) == 0


def test_tracer_injected_sleep_lands_in_span_duration(tracer_reset):
    tr = SpanTracer(
        enabled=True, inject_stage="build", inject_sleep_ms=50.0,
        inject_every=2,
    )
    for _ in range(4):
        with tr.span("build"):
            pass
    with tr.span("detect"):
        pass
    builds = [s for s in tr.snapshot() if s.name == "build"]
    slow = [s for s in builds if s.dur_us >= 45_000]
    fast = [s for s in builds if s.dur_us < 45_000]
    assert len(slow) == 2 and len(fast) == 2  # every 2nd injected
    detect = [s for s in tr.snapshot() if s.name == "detect"]
    assert detect[0].dur_us < 45_000  # only the named stage sleeps


def test_stage_timings_emit_spans_under_pinned_ctx(
    registry, tracer_reset
):
    from microrank_tpu.utils.profiling import StageTimings

    tr = configure_tracer(ObsConfig())
    ctx = tr.new_trace("win-7")
    timings = StageTimings(ctx=ctx)

    def off_thread():
        with timings.stage("rank_wait"):
            pass

    t = threading.Thread(target=off_thread)
    t.start()
    t.join()
    spans = tr.snapshot()
    assert [s.name for s in spans] == ["rank_wait"]
    # The pinned ctx wins even though the recording thread had no
    # ambient context — late async stages attribute correctly.
    assert spans[0].trace_id == "win-7"
    assert spans[0].parent_id == ctx.span_id
    assert timings.as_dict()["rank_wait"] >= 0.0


# ---------------------------------------------------- flight record formats


def test_flight_dump_formats_and_rate_limit(
    registry, tracer_reset, tmp_path
):
    cfg = ObsConfig(flight_min_interval_seconds=60.0)
    tr = configure_tracer(cfg)
    ctx = tr.new_trace("win-1")
    with tr.attach(ctx):
        with tr.span("detect", service="stream"):
            with tr.span("build", service="pipeline"):
                pass
    fr = FlightRecorder(tmp_path, cfg)
    d = fr.dump("incident")
    assert d is not None and d.parent.name == "flight"
    # Perfetto/Chrome form: X events + thread_name metadata.
    trace = json.loads((d / "trace.json").read_text())
    phs = {e["ph"] for e in trace["traceEvents"]}
    assert phs == {"X", "M"}
    named = {
        e["name"] for e in trace["traceEvents"] if e["ph"] == "X"
    }
    assert named == {"detect", "build"}
    # MicroRank's own schema: loadable by the pipeline's own ingest.
    from microrank_tpu.io import load_traces_csv

    df = load_traces_csv(d / "spans.csv")
    assert len(df) == 2
    assert set(df["traceID"]) == {"win-1"}
    assert set(df["operationName"]) == {"detect", "build"}
    # Parent links survive the CSV round trip.
    by_op = df.set_index("operationName")
    assert by_op.loc["build", "ParentSpanId"] in set(df["spanID"])
    man = json.loads((d / "manifest.json").read_text())
    assert man["spans"] == 2 and man["traces"] == 1
    assert (d / "metrics.json").exists() and (d / "metrics.prom").exists()
    # Rate limit: a second dump within the interval is suppressed.
    assert fr.dump("incident") is None
    from microrank_tpu.obs.metrics import flight_dumps

    assert flight_dumps().value(reason="incident") == 1
    assert flight_dumps().value(reason="suppressed") == 1


def test_journal_run_end_and_flight_dump_fsync(
    registry, tracer_reset, tmp_path, monkeypatch
):
    """Durability satellite: run_end and every flight dump flush+fsync
    the journal, so a crash never truncates the last incident's
    events."""
    from microrank_tpu.obs.journal import RunJournal

    # Count JOURNAL syncs specifically (patching os.fsync globally
    # would also count the atomic tmp+fsync+rename writers the flight
    # dump's snapshot files now go through — utils.atomic).
    synced = []
    real_sync = RunJournal.sync
    monkeypatch.setattr(
        RunJournal, "sync",
        lambda self: (synced.append(self.path), real_sync(self)),
    )
    j = RunJournal(tmp_path / "journal.jsonl")
    j.emit("window", start="w0")
    assert synced == []          # plain emits stay cheap
    j.run_end(windows=1)
    assert len(synced) == 1      # run_end fsyncs
    cfg = ObsConfig(flight_min_interval_seconds=0.0)
    tr = configure_tracer(cfg)
    with tr.span("detect"):
        pass
    fr = FlightRecorder(tmp_path, cfg, journal=j)
    d = fr.dump("incident")
    assert len(synced) == 2      # the dump fsyncs before correlating
    events = (d / "events.jsonl").read_text().splitlines()
    assert any('"window"' in e for e in events)


# ------------------------------------------------- propagation: stream


def test_stream_engine_propagates_trace_across_threads(
    registry, tracer_reset, tmp_path
):
    """Satellite: trace context flows engine thread -> build worker
    pool -> dispatch; the flight dump on incident open exists."""
    from microrank_tpu.stream import StreamEngine

    cfg = _stream_cfg()
    engine = StreamEngine(cfg, _stream_source(), out_dir=tmp_path)
    s = engine.run()
    assert s.ranked >= 2 and s.incidents_opened == 1
    tr = get_tracer()
    spans = tr.snapshot()
    by_name = {}
    for sp in spans:
        by_name.setdefault(sp.name, []).append(sp)
    # Builds ran on pool workers, under their windows' traces.
    builds = by_name["build"]
    assert all(b.trace_id.startswith("win-") for b in builds)
    assert any("build" in b.thread for b in builds), (
        "no build recorded on a pool worker thread"
    )
    # The dispatch spans share a ranked window's trace (the burst
    # head), and parent-link transitively to that window's root.
    roots = {
        sp.trace_id: sp.span_id for sp in by_name["window"]
    }
    disp = by_name["device_dispatch"]
    assert disp and all(d.trace_id in roots for d in disp)
    ids = {sp.span_id: sp for sp in spans}
    for d in disp:
        hop, seen = d, set()
        while hop.parent_id in ids and hop.span_id not in seen:
            seen.add(hop.span_id)
            hop = ids[hop.parent_id]
        # The chain must terminate AT the window's root span.
        assert hop.span_id == roots[d.trace_id]
    # Incident lifecycle spans exist for ranked AND healthy windows.
    assert len(by_name["incident"]) >= 4
    # Flight dump triggered by the incident opening.
    dumps = list((tmp_path / "flight").iterdir())
    assert len(dumps) == 1 and "incident" in dumps[0].name


# -------------------------------------------------- propagation: serve


def _serve_service(case, tmp_path=None, **serve_kw):
    from microrank_tpu.serve import ServeService

    serve_kw.setdefault("warmup", False)
    serve_kw.setdefault("max_wait_ms", 200.0)
    cfg = MicroRankConfig(
        serve=ServeConfig(**serve_kw),
        obs=ObsConfig(flight_min_interval_seconds=0.0),
        runtime=RuntimeConfig(prefer_bf16=False),
    )
    svc = ServeService(cfg, out_dir=tmp_path)
    svc.fit_baseline(case.normal)
    return svc


def test_serve_scheduler_and_pool_propagate_request_trace(
    registry, tracer_reset,
):
    """Satellite: the request trace (trace_id = request_id) crosses the
    scheduler thread AND the serve build pool."""
    from microrank_tpu.serve import RankRequest

    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    svc = _serve_service(case, build_workers=2)
    svc.add_dataset("case", case.abnormal)
    svc.start()
    try:
        fut = svc.submit(
            RankRequest(request_id="req-traced", dataset="case")
        )
        result = fut.result(timeout=120)
        assert result.ranking
    finally:
        svc.shutdown()
    spans = [
        s for s in get_tracer().snapshot()
        if s.trace_id == "req-traced"
    ]
    names = {s.name for s in spans}
    assert {"parse", "detect", "build", "request"} <= names
    assert "device_dispatch" in names  # batch head == this request
    build = next(s for s in spans if s.name == "build")
    assert "serve-build" in build.thread  # built on the pool, not the
    # scheduler thread — the context crossed both hops
    root = next(s for s in spans if s.name == "request")
    parse = next(s for s in spans if s.name == "parse")
    assert parse.parent_id == root.span_id


def test_flight_dump_on_injected_degraded_dispatch(
    registry, tracer_reset, tmp_path
):
    """Satellite: ServeConfig.inject_dispatch_failures drives the
    degradation path; the flight recorder dumps on it."""
    from microrank_tpu.serve import RankRequest

    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    svc = _serve_service(
        case, tmp_path=tmp_path, inject_dispatch_failures=2,
        fallback=True, build_workers=0,
    )
    svc.add_dataset("case", case.abnormal)
    svc.start()
    try:
        fut = svc.submit(RankRequest(request_id="r1", dataset="case"))
        result = fut.result(timeout=120)
        assert result.degraded and result.ranking
    finally:
        svc.shutdown()
    dumps = sorted((tmp_path / "flight").iterdir())
    reasons = {d.name.rsplit("-", 1)[-1] for d in dumps}
    assert "degraded" in reasons
    degraded = next(d for d in dumps if d.name.endswith("degraded"))
    assert (degraded / "spans.csv").exists()
    assert (degraded / "trace.json").exists()
    # SIGTERM-drain dump also fires at shutdown (same recorder).
    assert "sigterm" in reasons


# ----------------------------------------------------------- /profilez


def test_profilez_endpoint_captures_session(registry, tmp_path):
    from microrank_tpu.obs.server import start_metrics_server

    server = start_metrics_server(0, profile_dir=tmp_path / "profiles")
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/profilez?seconds=0.1",
            timeout=60,
        ) as r:
            body = json.loads(r.read())
        assert body["seconds"] == 0.1
        session = Path(body["session"])
        assert session.exists()
        assert list(session.rglob("*")), "empty profiler session"
    finally:
        server.close()
    from microrank_tpu.obs.metrics import profile_sessions

    assert profile_sessions().value(trigger="endpoint") == 1


# ------------------------------------------------------------- dogfood


def _flight_spans_csv(tmp_path, tag, inject_ms):
    from microrank_tpu.stream import StreamEngine

    out = tmp_path / tag
    cfg = _stream_cfg(
        inject_stage="build", inject_stage_sleep_ms=inject_ms
    )
    engine = StreamEngine(cfg, _stream_source(), out_dir=out)
    s = engine.run()
    assert s.ranked >= 2, "fixture drifted: no ranked windows"
    dump = engine.flight.dump("run_end")  # complete-ring dump
    return dump / "spans.csv"


def test_dogfood_flight_selfrank_blames_slowed_stage(
    registry, tracer_reset, tmp_path
):
    """THE acceptance test: slow the build pool by an injected sleep,
    flight-dump both a healthy and the degraded run, and run the full
    MicroRank CLI over the two dumps — the pipeline must rank its own
    slowed stage top-1, with tie-aware scoring."""
    normal_csv = _flight_spans_csv(tmp_path, "healthy", 0.0)
    abnormal_csv = _flight_spans_csv(tmp_path, "slowed", 250.0)

    from microrank_tpu.cli.main import main

    out = tmp_path / "selfrank"
    rc = main(
        [
            "run",
            "--normal", str(normal_csv),
            "--abnormal", str(abnormal_csv),
            "-o", str(out),
            "--engine", "pandas",
        ]
    )
    assert rc == 0
    windows = [
        json.loads(line)
        for line in (out / "windows.jsonl").read_text().splitlines()
    ]
    ranked = [w for w in windows if w["ranking"]]
    assert ranked, "self-rank produced no ranked window"
    ranking = ranked[-1]["ranking"]
    # Tie-aware top-1: the group tied with the best score must be
    # exactly the slowed stage (pod-level name <service>_<stage>).
    top_score = ranking[0][1]
    tied = {
        name
        for name, score in ranking
        if score >= top_score - 1e-6 * max(abs(top_score), 1e-12)
    }
    assert tied == {"pipeline_build"}, ranking[:5]


# ---------------------------------------------------------- table lane


def test_table_lane_windows_carry_trace_ids(
    registry, tracer_reset, tmp_path
):
    """Offline runs trace identically: each window's stages share one
    win-<start> trace (the StageTimings ctx pin)."""
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.pipeline.table_runner import TableRCA

    case = generate_case(
        SyntheticConfig(n_operations=20, n_kinds=6, n_traces=80, seed=7)
    )
    case.normal.to_csv(tmp_path / "n.csv", index=False)
    case.abnormal.to_csv(tmp_path / "a.csv", index=False)
    rca = TableRCA(
        MicroRankConfig(runtime=RuntimeConfig(prefer_bf16=False))
    )
    rca.fit_baseline(native.load_span_table(tmp_path / "n.csv"))
    results = rca.run(native.load_span_table(tmp_path / "a.csv"))
    assert any(r.ranking for r in results)
    spans = get_tracer().snapshot()
    win_traces = {
        s.trace_id for s in spans if s.trace_id.startswith("win-")
    }
    assert win_traces, "table lane recorded no window traces"
    names = {s.name for s in spans}
    assert "detect" in names and "rank_dispatch" in names
