"""Crash-only streaming (chaos/): checkpoint round-trip + corruption
rejection, atomic-write crash injection (old checkpoint survives a kill
between tmp and rename), the unified retry policy (backoff, jitter,
circuit breaker states), seeded FaultPlan determinism, the webhook
sink's bounded retry queue + drop accounting, serve per-request
deadline_ms expiry, and the acceptance paths: an in-process
stop-and-resume run plus the real thing — a stream subprocess SIGKILLed
mid-incident and restarted with ``--resume`` re-opens ZERO duplicate
incidents, keeps its baseline (no cold-start re-seed) and resumes the
source at its checkpointed cursor. All on CPU jax.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from microrank_tpu.chaos import (
    CheckpointError,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    RetryPolicy,
    configure_chaos,
    get_breaker,
    load_checkpoint,
    maybe_inject,
    reset_breakers,
    retry_call,
    save_checkpoint,
)
from microrank_tpu.config import ChaosConfig, MicroRankConfig, StreamConfig
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.stream import (
    IncidentTracker,
    OnlineBaseline,
    StreamEngine,
    StreamWindower,
    SyntheticSource,
    WebhookIncidentSink,
)
from microrank_tpu.testing import SyntheticConfig, generate_case

T0 = pd.Timestamp("2025-03-01 00:00:00")


@pytest.fixture(autouse=True)
def chaos_isolation():
    """Fresh registry + disarmed plan + closed breakers per test —
    chaos state is process-global by design; tests must not leak it."""
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    configure_chaos(MicroRankConfig())   # plan = None
    reset_breakers()
    yield reg
    configure_chaos(MicroRankConfig())
    reset_breakers()
    set_registry(old)


registry = chaos_isolation  # alias for readability at use sites


def _chaos_cfg(*fault_dicts, seed=0, **stream_kw):
    stream_kw.setdefault("allowed_lateness_seconds", 5.0)
    return MicroRankConfig(
        stream=StreamConfig(**stream_kw),
        chaos=ChaosConfig(
            enabled=True, seed=seed, faults=tuple(fault_dicts)
        ),
    )


# ------------------------------------------------------- checkpoint IO


def test_checkpoint_round_trip_rejects_corruption(tmp_path):
    path = tmp_path / "state.ckpt"
    payload = {"a": [1, 2, 3], "b": {"c": "x"}}
    save_checkpoint(path, payload)
    assert load_checkpoint(path) == payload
    # Bit rot in the payload: checksum rejects.
    doc = json.loads(path.read_text())
    doc["payload"]["a"] = [1, 2, 4]
    path.write_text(json.dumps(doc))
    with pytest.raises(CheckpointError, match="checksum"):
        load_checkpoint(path)
    # Torn JSON (the non-atomic writer this module replaces).
    path.write_text('{"version": 1, "payload": {"a"')
    with pytest.raises(CheckpointError, match="torn"):
        load_checkpoint(path)
    # A future version is refused, not half-understood.
    save_checkpoint(path, payload)
    doc = json.loads(path.read_text())
    doc["version"] = 999
    path.write_text(json.dumps(doc))
    with pytest.raises(CheckpointError, match="version"):
        load_checkpoint(path)
    with pytest.raises(CheckpointError, match="unreadable"):
        load_checkpoint(tmp_path / "missing.ckpt")


def test_checkpoint_write_crash_between_tmp_and_rename(tmp_path):
    """Acceptance: an injected crash BETWEEN the durable tmp write and
    the rename leaves the previous checkpoint fully loadable."""
    path = tmp_path / "state.ckpt"
    save_checkpoint(path, {"gen": 1})
    configure_chaos(
        _chaos_cfg({"seam": "checkpoint", "kind": "crash", "count": 1})
    )
    with pytest.raises(InjectedFault):
        save_checkpoint(path, {"gen": 2})
    assert load_checkpoint(path) == {"gen": 1}   # old ckpt survives
    # The plan's count is spent: the next write goes through.
    save_checkpoint(path, {"gen": 3})
    assert load_checkpoint(path) == {"gen": 3}


# -------------------------------------------------- state round trips


def test_baseline_state_round_trip_preserves_p2_markers():
    rng = np.random.default_rng(0)
    ob = OnlineBaseline(decay=0.3, slo_stat="p90")
    n = 400
    frame = pd.DataFrame(
        {
            "traceID": [f"t{i}" for i in range(n)],
            "serviceName": ["svcA"] * n,
            "operationName": ["op"] * n,
            "duration": (rng.lognormal(2.0, 0.5, n) * 1000).astype(int),
            "startTime": [T0] * n,
            "endTime": [T0] * n,
        }
    )
    ob.update(frame)
    ob.freeze()
    state = json.loads(json.dumps(ob.to_state()))   # via-JSON fidelity
    twin = OnlineBaseline(decay=0.3, slo_stat="p90")
    twin.restore(state)
    v1, b1 = ob.snapshot()
    v2, b2 = twin.snapshot()
    assert v1.names == v2.names
    np.testing.assert_array_equal(b1.mean_ms, b2.mean_ms)
    np.testing.assert_array_equal(b1.std_ms, b2.std_ms)
    assert twin.frozen and twin.n_updates == ob.n_updates
    assert twin.ready == ob.ready
    # A mismatched SLO statistic is an unusable checkpoint, not a
    # silent misread of p99 markers as means.
    with pytest.raises(ValueError, match="slo_stat"):
        OnlineBaseline(decay=0.3, slo_stat="mean").restore(state)


def test_incident_tracker_state_round_trip_dedups_after_restore():
    tr = IncidentTracker(top_k=3, resolve_after=2, cooldown_windows=2)
    rank = [("a", 1.0), ("b", 0.8), ("c", 0.6)]
    inc = tr.observe_ranked("w1", rank)
    state = json.loads(json.dumps(tr.to_state()))
    twin = IncidentTracker(top_k=3, resolve_after=2, cooldown_windows=2)
    twin.restore(state)
    assert twin.has_open and twin.opened == 1
    # The restarted run's abnormal window DEDUPS into the restored
    # incident instead of opening a duplicate.
    again = twin.observe_ranked("w2", rank)
    assert again is not None
    assert again.incident_id == inc.incident_id
    assert twin.opened == 1
    resolved = [twin.observe_healthy(f"w{i}") for i in (3, 4)]
    assert [i.incident_id for i in resolved[1]] == [inc.incident_id]
    # Cooldown survives the round trip too.
    state2 = twin.to_state()
    twin2 = IncidentTracker(top_k=3, resolve_after=2, cooldown_windows=2)
    twin2.restore(state2)
    assert twin2.observe_ranked("w5", rank) is None   # suppressed
    assert twin2.suppressed == 1


def test_windower_state_round_trip_keeps_buffers_and_cursor():
    def spans(*offsets_s, tag="s"):
        return pd.DataFrame(
            {
                "traceID": [f"{tag}{i}" for i in range(len(offsets_s))],
                "startTime": [
                    T0 + pd.Timedelta(seconds=o) for o in offsets_s
                ],
                "off": list(offsets_s),
            }
        )

    w = StreamWindower(width_us=60_000_000)
    closed = w.add(spans(10, 70, 80))     # [0,60) closes; [60,120) open
    assert len(closed) == 1
    state = json.loads(json.dumps(w.to_state()))
    twin = StreamWindower(width_us=60_000_000)
    twin.restore(state)
    assert twin._next == 1 and twin.max_event_us == w.max_event_us
    # The buffered open window survives: later spans close it with the
    # buffered content intact, and nothing re-emits window 0.
    out = twin.add(spans(130, tag="n"))
    assert [sorted(c.frame["off"]) for c in out] == [[70, 80]]
    # Mismatched geometry rejects (a resumed run must window alike).
    with pytest.raises(ValueError, match="geometry"):
        StreamWindower(width_us=30_000_000).restore(state)


# --------------------------------------------------------- fault plan


def test_fault_plan_counting_and_determinism():
    specs = [
        {"seam": "dispatch", "kind": "fail", "after": 1, "count": 2},
        {"seam": "webhook", "kind": "hang", "value": 5.0,
         "every": 2, "count": -1},
    ]
    plan_a = FaultPlan([FaultSpec.from_dict(s) for s in specs], seed=7)
    plan_b = FaultPlan([FaultSpec.from_dict(s) for s in specs], seed=7)
    for plan in (plan_a, plan_b):
        fired = [
            plan.fire("dispatch") is not None for _ in range(5)
        ]
        # after=1, count=2: events 1 and 2 fire, then the spec is spent.
        assert fired == [False, True, True, False, False]
        wh = [plan.fire("webhook") is not None for _ in range(4)]
        assert wh == [True, False, True, False]    # every=2, unbounded
    assert plan_a.injected == plan_b.injected


def test_maybe_inject_kinds(registry):
    configure_chaos(
        _chaos_cfg(
            {"seam": "s1", "kind": "fail", "count": 1},
            {"seam": "s2", "kind": "stall", "value": 80.0, "count": 1},
            {"seam": "s3", "kind": "nan", "count": 1},
        )
    )
    with pytest.raises(InjectedFault):
        maybe_inject("s1")
    assert maybe_inject("s1") is None               # count spent
    slept = []
    act = maybe_inject("s2", sleep=slept.append)    # sleeping kind
    assert act["kind"] == "stall" and slept == [0.08]
    act = maybe_inject("s3")                        # caller-interpreted
    assert act["kind"] == "nan"
    inj = registry.get("microrank_fault_injections_total")
    assert sum(s["value"] for s in inj.samples()) == 3


# -------------------------------------------------------- retry policy


def test_retry_call_backoff_and_metrics(registry):
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    policy = RetryPolicy(
        max_attempts=3, base_delay_s=0.1, jitter=0.0, breaker_threshold=99
    )
    out = retry_call("t_seam", flaky, policy=policy, sleep=sleeps.append)
    assert out == "ok" and calls["n"] == 3
    # Exponential, no jitter: 0.1 then 0.2.
    assert sleeps == pytest.approx([0.1, 0.2])
    assert registry.get("microrank_retry_attempts_total").value(
        seam="t_seam"
    ) == 2
    # Exhaustion re-raises and is counted.
    with pytest.raises(RuntimeError, match="always"):
        retry_call(
            "t_seam2",
            lambda: (_ for _ in ()).throw(RuntimeError("always")),
            policy=RetryPolicy(max_attempts=2, base_delay_s=0.0,
                               breaker_threshold=99),
            sleep=lambda s: None,
        )
    assert registry.get("microrank_retry_exhausted_total").value(
        seam="t_seam2"
    ) == 1


def test_circuit_breaker_open_half_open_close(registry):
    from microrank_tpu.chaos import BreakerOpen

    now = {"t": 0.0}
    policy = RetryPolicy(
        max_attempts=1, breaker_threshold=3, breaker_reset_s=10.0
    )
    br = get_breaker("br_seam", policy)
    br.clock = lambda: now["t"]
    boom = lambda: (_ for _ in ()).throw(RuntimeError("down"))
    for _ in range(3):
        with pytest.raises(RuntimeError):
            retry_call("br_seam", boom, policy=policy, sleep=lambda s: None)
    assert br.state == "open"
    assert registry.get("microrank_breaker_state").value(
        seam="br_seam"
    ) == 1.0
    # Open: fast-fail without calling fn.
    with pytest.raises(BreakerOpen):
        retry_call(
            "br_seam", lambda: "never", policy=policy, sleep=lambda s: None
        )
    # Reset window elapses: the next call is the half-open probe; its
    # success closes the breaker.
    now["t"] = 11.0
    assert retry_call(
        "br_seam", lambda: "ok", policy=policy, sleep=lambda s: None
    ) == "ok"
    assert br.state == "closed"
    assert registry.get("microrank_breaker_state").value(
        seam="br_seam"
    ) == 0.0
    # A failing probe re-opens immediately.
    for _ in range(3):
        with pytest.raises(RuntimeError):
            retry_call("br_seam", boom, policy=policy, sleep=lambda s: None)
    now["t"] = 22.0
    with pytest.raises(RuntimeError):
        retry_call("br_seam", boom, policy=policy, sleep=lambda s: None)
    assert br.state == "open"


# ------------------------------------------------------- webhook queue


def test_webhook_retry_queue_backoff_and_drop(registry):
    """A failed POST parks in the bounded queue and retries with
    backoff on later traffic; max_attempts exhaustion drops + counts."""
    now = {"t": 0.0}
    sink = WebhookIncidentSink(
        "http://127.0.0.1:9/unroutable",
        timeout=0.2,
        max_attempts=3,
        max_queue=4,
        clock=lambda: now["t"],
    )
    sink.emit({"event": "incident_open", "top": []})
    assert sink.failures == 1 and sink.pending() == 1
    # Not yet due: flush is a no-op.
    sink.flush()
    assert sink.failures == 1
    # Due entries re-send (and fail again) as the clock advances.
    now["t"] = 60.0
    sink.flush()
    assert sink.failures == 2 and sink.pending() == 1
    now["t"] = 120.0
    sink.flush()    # third attempt == max_attempts -> dropped
    assert sink.pending() == 0 and sink.dropped == 1
    assert registry.get("microrank_webhook_dropped_total").value() == 1
    # Queue overflow evicts (and counts) the oldest entry.
    for i in range(6):
        sink.emit({"event": f"e{i}", "top": []})
    assert sink.pending() == 4
    assert sink.dropped == 1 + 2


# ------------------------------------------------------ source cursors


def test_file_tail_source_cursor_restore(tmp_path, registry):
    from microrank_tpu.stream import FileTailSource

    case = generate_case(
        SyntheticConfig(n_operations=10, n_traces=40, seed=2)
    )
    df = case.normal
    csv = tmp_path / "grow.csv"
    half = len(df) // 2
    df.iloc[:half].to_csv(csv, index=False)
    src = FileTailSource(csv, poll_seconds=0, max_polls=2,
                         sleep=lambda s: None)
    first = next(iter(src))
    assert len(first) == half
    cursor = src.checkpoint_state()
    assert cursor["offset"] > 0 and cursor["signature"]
    # A NEW source (a restarted process) restored at the cursor yields
    # only the rows appended after it.
    df.iloc[half:].to_csv(csv, mode="a", header=False, index=False)
    src2 = FileTailSource(csv, poll_seconds=0, max_polls=2,
                          sleep=lambda s: None)
    src2.restore_state(cursor)
    batches = list(src2)
    assert sum(len(b) for b in batches) == len(df) - half
    # Rotation invalidates the cursor: a different file re-reads fully.
    csv.write_text("")  # truncate
    df.iloc[:half].rename(columns={"traceID": "traceID2"}).rename(
        columns={"traceID2": "traceID"}
    ).to_csv(csv, index=False)
    src3 = FileTailSource(csv, poll_seconds=0, max_polls=2,
                          sleep=lambda s: None)
    bad = dict(cursor)
    bad["signature"] = "not-the-header"
    src3.restore_state(bad)
    batches = list(src3)
    assert sum(len(b) for b in batches) == half   # full re-read


def test_replay_source_cursor_restore():
    from microrank_tpu.stream import ReplaySource

    df = pd.DataFrame(
        {
            "traceID": [f"t{i}" for i in range(10)],
            "startTime": [
                T0 + pd.Timedelta(seconds=i) for i in range(10)
            ],
        }
    )
    src = ReplaySource(df, chunk_spans=3)
    it = iter(src)
    next(it), next(it)
    assert src.rows_emitted == 6
    twin = ReplaySource(df, chunk_spans=3)
    twin.restore_state(src.checkpoint_state())
    rest = list(twin)
    assert sum(len(c) for c in rest) == 4
    assert list(rest[0]["traceID"])[0] == "t6"


# ----------------------------------------- engine chaos + stop/resume


def _synthetic_source(**kw):
    kw.setdefault("n_windows", 8)
    kw.setdefault("faulted", [3])
    kw.setdefault(
        "synth_config",
        SyntheticConfig(n_operations=24, n_traces=200, n_kinds=16, seed=5),
    )
    kw.setdefault("pace_seconds", 0.0)
    return SyntheticSource(**kw)


def test_engine_fault_plan_zero_dropped_windows(registry, tmp_path):
    """Acceptance: a seeded FaultPlan across >= 5 distinct seams —
    dispatch fail, build fail, fetch NaN poison, source stall, webhook
    hang — completes with ZERO dropped windows (every abnormal window
    still ranks; the retries absorb the faults) and every injection
    visible in the retry/fault metrics and the journal."""
    cfg = _chaos_cfg(
        {"seam": "dispatch", "kind": "fail", "count": 1},
        {"seam": "build", "kind": "fail", "count": 1},
        {"seam": "fetch", "kind": "nan", "count": 1},
        {"seam": "source_stall", "kind": "stall", "value": 10.0,
         "count": 1},
        {"seam": "webhook", "kind": "hang", "value": 10.0, "count": 1},
        seed=3,
        webhook_url="http://127.0.0.1:9/unroutable",
        webhook_timeout_seconds=0.2,
    )
    src = _synthetic_source(faulted=[3, 4])
    eng = StreamEngine(cfg, src, out_dir=tmp_path)
    s = eng.run()
    assert s.windows == 8
    assert s.ranked == 2 and s.skipped == 0     # zero dropped windows
    assert s.incidents_opened == 1 and s.incidents_resolved == 1
    inj = registry.get("microrank_fault_injections_total")
    seams = {smp["labels"]["seam"] for smp in inj.samples()}
    assert {
        "dispatch", "build", "fetch", "source_stall", "webhook"
    } <= seams
    # dispatch + fetch retries ride the unified counter; build retries
    # happen on the pool under the same surface.
    retries = registry.get("microrank_retry_attempts_total")
    by_seam = {
        smp["labels"]["seam"]: smp["value"] for smp in retries.samples()
    }
    assert by_seam.get("stream_dispatch", 0) >= 2   # fail + nan poison
    assert by_seam.get("build", 0) >= 1
    # Breaker gauges exposed (closed) for the retried seams.
    br = registry.get("microrank_breaker_state")
    assert br.value(seam="stream_dispatch") == 0.0
    # Journal carries the fault_injected trail.
    from microrank_tpu.obs import read_journal

    faults = [
        e
        for e in read_journal(tmp_path / "journal.jsonl")
        if e["event"] == "fault_injected"
    ]
    assert {f["seam"] for f in faults} >= {
        "dispatch", "build", "fetch", "source_stall", "webhook"
    }


def test_engine_stop_and_resume_no_duplicate_incident(
    registry, tmp_path
):
    """In-process half of the kill-resume acceptance: stop a run
    mid-incident (max_windows), resume a FRESH engine from the
    checkpoint, and the restarted run dedups into the SAME incident
    (zero duplicate opens), skips re-ranking finalized windows, and
    re-enters no cold start."""
    cfg = _chaos_cfg(max_windows=5)          # stop with the incident open
    src = _synthetic_source(faulted=[3, 4])
    eng = StreamEngine(cfg, src, out_dir=tmp_path)
    s1 = eng.run()
    assert s1.windows == 5 and s1.incidents_opened == 1
    assert s1.incidents_resolved == 0        # still open at the stop
    ckpt = load_checkpoint(tmp_path / "state.ckpt")
    assert ckpt["tracker"]["open"], "checkpoint must carry the incident"
    assert ckpt["source"]["row"] > 0
    # A fresh process: new engine, new (deterministically regenerated)
    # source, resume=True.
    cfg2 = _chaos_cfg()                      # run to the end this time
    src2 = _synthetic_source(faulted=[3, 4])
    eng2 = StreamEngine(cfg2, src2, out_dir=tmp_path, resume=True)
    assert eng2.resumed
    s2 = eng2.run()
    # Continuity: totals continue the first run's counters.
    assert s2.windows == 8
    assert s2.incidents_opened == 1 and s2.incidents_resolved == 1
    assert s2.warmup == 0                    # no cold-start re-seed
    events = [
        json.loads(line)
        for line in (tmp_path / "incidents.jsonl").read_text().splitlines()
    ]
    opens = [e for e in events if e["event"] == "incident_open"]
    resolves = [e for e in events if e["event"] == "incident_resolve"]
    assert len(opens) == 1, "duplicate incident_open after resume"
    assert len(resolves) == 1
    assert opens[0]["incident_id"] == resolves[0]["incident_id"]
    # No window processed twice, in order, none lost: the two runs'
    # window events tile the timeline.
    from microrank_tpu.obs import read_journal

    jev = read_journal(tmp_path / "journal.jsonl")
    starts = [e["start"] for e in jev if e["event"] == "window"]
    assert len(starts) == 8 and len(set(starts)) == 8
    assert starts == sorted(starts)
    run_starts = [e for e in jev if e["event"] == "run_start"]
    assert [r.get("resumed") for r in run_starts] == [False, True]


def test_engine_rejects_corrupt_checkpoint_and_cold_starts(
    registry, tmp_path
):
    (tmp_path / "state.ckpt").write_text("{ torn garbage")
    src = _synthetic_source()
    eng = StreamEngine(
        _chaos_cfg(), src, out_dir=tmp_path, resume=True
    )
    assert not eng.resumed                   # rejected, not half-loaded
    assert registry.get("microrank_checkpoint_events_total").value(
        event="rejected"
    ) == 1
    s = eng.run()
    assert s.windows == 8 and s.incidents_opened == 1


# --------------------------------------------------- serve deadline_ms


def test_parse_rank_request_deadline_validation():
    from microrank_tpu.serve import ProtocolError, parse_rank_request

    req = parse_rank_request(
        json.dumps({"dataset": "d", "deadline_ms": 250}).encode()
    )
    assert req.deadline_ms == 250.0
    with pytest.raises(ProtocolError, match="deadline_ms"):
        parse_rank_request(
            json.dumps({"dataset": "d", "deadline_ms": -1}).encode()
        )
    with pytest.raises(ProtocolError, match="deadline_ms"):
        parse_rank_request(
            json.dumps({"dataset": "d", "deadline_ms": "soon"}).encode()
        )


def test_serve_deadline_expires_queued_request(registry, tmp_path):
    """A request whose deadline elapsed in the queue expires BEFORE
    staging (504 path, outcome 'expired', journal event) — the batch
    never dispatches device work nobody is waiting for."""
    from concurrent.futures import Future

    from microrank_tpu.config import ServeConfig
    from microrank_tpu.serve import DeadlineExceeded, RankRequest
    from microrank_tpu.serve.server import ServeService

    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    cfg = MicroRankConfig(
        serve=ServeConfig(warmup=False, build_workers=0)
    )
    svc = ServeService(cfg, out_dir=tmp_path)
    svc.fit_baseline(case.normal)
    outcomes = []
    svc._on_done = (  # observe without the HTTP stack
        lambda pw, err: outcomes.append(type(err).__name__ if err else None)
    )
    req = RankRequest(
        request_id="r-exp", dataset="case", deadline_ms=50.0
    )
    fut = Future()
    stale = (req, fut, time.monotonic() - 1.0, svc._on_done, None)
    svc.scheduler._process(stale)
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=5)
    assert outcomes == ["DeadlineExceeded"]
    from microrank_tpu.obs import read_journal

    expired = [
        e
        for e in read_journal(tmp_path / "journal.jsonl")
        if e["event"] == "request_deadline_expired"
    ]
    assert len(expired) == 1 and expired[0]["stage"] == "queue"
    # The batcher half: a parked window past its deadline is expired at
    # dispatch time instead of riding the device batch.
    from microrank_tpu.pipeline.results import WindowResult
    from microrank_tpu.serve.batcher import PendingWindow

    pw = PendingWindow(
        request=RankRequest(
            request_id="r-exp2", dataset="case", deadline_ms=50.0
        ),
        result=WindowResult(start="", end="", anomaly=True),
        span_df=None, normal_ids=[], abnormal_ids=[], graph=None,
        op_names=[], kernel="packed", future=Future(),
        enqueued=time.monotonic() - 1.0, built=time.monotonic(),
    )
    svc.scheduler.batcher.dispatch([pw])
    with pytest.raises(DeadlineExceeded):
        pw.future.result(timeout=5)
    assert svc.scheduler.batcher.dispatches == 0


# --------------------------------------------------- atomic file writes


def test_atomic_writers_used_for_snapshots_and_manifest(tmp_path):
    """The warm-start inputs (metrics snapshot, warmup manifest,
    explain bundle) all go through tmp+fsync+rename now: no *.tmp.*
    litter on success, and a reader never sees a torn file."""
    from microrank_tpu.dispatch import record_manifest_entry
    from microrank_tpu.obs.metrics import ensure_catalog

    reg = get_registry()
    ensure_catalog()
    reg.write_snapshot(tmp_path)
    assert json.loads((tmp_path / "metrics.json").read_text())["metrics"]
    assert (tmp_path / "metrics.prom").read_text()
    record_manifest_entry(str(tmp_path), "stream", "packed", [1, 2])
    man = json.loads((tmp_path / "warmup_manifest.json").read_text())
    assert man["programs"][0]["occupancies"] == [1, 2]
    assert not list(tmp_path.glob("*.tmp.*"))


# ------------------------------------------------ kill -9 + --resume e2e


def _metric_total(prom_text: str, name: str, label: str = None) -> float:
    total = 0.0
    for line in prom_text.splitlines():
        if not line.startswith(name):
            continue
        if label is not None and label not in line:
            continue
        total += float(line.rsplit(" ", 1)[1])
    return total


def test_stream_sigkill_resume_e2e(tmp_path):
    """THE acceptance path: a real `cli stream` process SIGKILLed
    mid-incident, restarted with --resume — zero duplicate
    incident_open, baseline continuity (no cold-start gating), source
    resumed at the checkpointed cursor (no window ranked twice)."""
    out_dir = tmp_path / "out"
    src = _synthetic_source(faulted=[3, 4])
    input_csv = tmp_path / "timeline.csv"
    normal_csv = tmp_path / "normal.csv"
    src.timeline.timeline.to_csv(input_csv, index=False)
    src.normal.to_csv(normal_csv, index=False)
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(Path(__file__).parent.parent),
    }
    base_cmd = [
        sys.executable, "-m", "microrank_tpu.cli", "stream",
        "--source", "replay", "--input", str(input_csv),
        "--chunk-spans", "400", "--lateness-seconds", "5",
        "-o", str(out_dir),
    ]
    # Run 1: paced so the kill lands mid-run, seeded from the normal
    # dump (run 2 passes no --normal: only the checkpoint can arm it).
    proc = subprocess.Popen(
        base_cmd + ["--normal", str(normal_csv), "--pace-seconds", "0.3"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    inc_log = out_dir / "incidents.jsonl"
    ckpt_path = out_dir / "state.ckpt"
    killed_mid_incident = False
    deadline = time.time() + 300
    try:
        while time.time() < deadline and proc.poll() is None:
            if ckpt_path.exists():
                try:
                    ck = load_checkpoint(ckpt_path)
                except CheckpointError:
                    ck = None
                if ck and ck["tracker"]["open"]:
                    os.kill(proc.pid, signal.SIGKILL)
                    killed_mid_incident = True
                    break
            time.sleep(0.05)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=60)
    assert killed_mid_incident, (
        "run finished before the kill — raise --pace-seconds"
    )
    opens_before = sum(
        1
        for line in inc_log.read_text().splitlines()
        if json.loads(line)["event"] == "incident_open"
    )
    assert opens_before == 1
    # Run 2: --resume, no --normal, unpaced.
    proc2 = subprocess.run(
        base_cmd + ["--resume"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    events = [
        json.loads(line)
        for line in inc_log.read_text().splitlines()
    ]
    opens = [e for e in events if e["event"] == "incident_open"]
    resolves = [e for e in events if e["event"] == "incident_resolve"]
    assert len(opens) == 1, "duplicate incident_open across the kill"
    assert len(resolves) == 1
    assert opens[0]["incident_id"] == resolves[0]["incident_id"]
    from microrank_tpu.obs import read_journal

    jev = read_journal(out_dir / "journal.jsonl")
    run_starts = [e for e in jev if e["event"] == "run_start"]
    assert len(run_starts) == 2
    assert run_starts[1]["resumed"] is True
    # Baseline continuity: nothing after the resume is a warmup window,
    # and no window was processed twice (unique, ordered starts).
    windows = [e for e in jev if e["event"] == "window"]
    assert all(
        w.get("skipped_reason") != "baseline_warmup" for w in windows
    )
    starts = [w["start"] for w in windows]
    assert len(starts) == len(set(starts)) == 8
    assert starts == sorted(starts)
    # Source cursor: run 2's final checkpoint consumed the whole replay.
    final = load_checkpoint(ckpt_path)
    assert final["source"]["row"] == len(src.timeline.timeline)
    # Run 2's snapshot shows a checkpoint restore and writes.
    prom = (out_dir / "metrics.prom").read_text()
    assert _metric_total(
        prom, "microrank_checkpoint_events_total{", 'event="restore"'
    ) == 1
    assert _metric_total(
        prom, "microrank_checkpoint_events_total{", 'event="write"'
    ) >= 1
