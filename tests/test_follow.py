"""Follow/tail mode (pipeline.follow): rank windows of a growing CSV as
they close, with cursor-checkpointed restarts — the "online RCA" the
reference's README advertises (README.md:40-47) made literal.
"""

import json
from pathlib import Path

import pandas as pd
import pytest

from microrank_tpu.config import MicroRankConfig, RuntimeConfig, WindowConfig
from microrank_tpu.native import load_span_table
from microrank_tpu.pipeline.follow import follow_table, run_follow
from microrank_tpu.pipeline.table_runner import TableRCA
from microrank_tpu.testing import SyntheticConfig
from microrank_tpu.testing.synthetic import generate_timeline


@pytest.fixture(scope="module")
def timeline():
    return generate_timeline(
        SyntheticConfig(n_operations=40, n_kinds=8, n_traces=120, seed=5),
        4,
        [0, 1, 2, 3],
    )


def _window_frame(tl, w):
    w0 = tl.start + pd.Timedelta(minutes=w * tl.window_minutes)
    w1 = w0 + pd.Timedelta(minutes=tl.window_minutes)
    df = tl.timeline
    return df[(df["startTime"] >= w0) & (df["startTime"] < w1)]


def _rca(tl, tmp_path):
    cfg = MicroRankConfig(
        window=WindowConfig(
            detect_minutes=tl.window_minutes, skip_minutes=0.0
        ),
        # Ingest caching off in the poll loop tests: every poll reloads
        # a grown file anyway, and sidecars would litter tmp_path.
        runtime=RuntimeConfig(),
    )
    rca = TableRCA(cfg)
    normal_csv = tmp_path / "normal.csv"
    if not normal_csv.exists():
        tl.normal.to_csv(normal_csv, index=False)
    rca.fit_baseline(load_span_table(normal_csv))
    return rca


def test_follow_ranks_windows_incrementally(timeline, tmp_path):
    """Appending spans while the follower polls emits each newly CLOSED
    window exactly once, in order, through the normal sink."""
    tl = timeline
    csv = tmp_path / "stream.csv"
    out = tmp_path / "out"
    # Two complete windows + the third one's spans up to its middle:
    # only windows 0 and 1 have closed (the horizon is the newest span
    # start, inside window 2).
    w0, w1, w2, w3 = (_window_frame(tl, w) for w in range(4))
    pd.concat([w0, w1, w2]).to_csv(csv, index=False)

    rca = _rca(tl, tmp_path)
    polls = follow_table(
        rca, csv, out, poll_seconds=0.0, idle_exit=1, sleep=lambda s: None
    )
    first = next(polls)
    starts1 = [r.start for r in first if r.ranking]
    assert len(starts1) == 2  # windows 0 and 1 closed; 2 still open

    # The stream grows: window 3 arrives, closing window 2 (horizon
    # moves into window 3).
    pd.concat([w0, w1, w2, w3]).to_csv(csv, index=False)
    second = next(polls)
    starts2 = [r.start for r in second if r.ranking]
    assert len(starts2) == 1  # ONLY window 2 — no re-ranking of 0/1
    assert starts2[0] not in starts1

    # No growth -> idle_exit stops the generator.
    with pytest.raises(StopIteration):
        next(polls)

    # The sink saw every ranked window once, in window order.
    lines = [
        json.loads(l)
        for l in (out / "windows.jsonl").read_text().splitlines()
    ]
    ranked = [l["start"] for l in lines if l["ranking"]]
    assert ranked == starts1 + starts2
    assert len(set(ranked)) == len(ranked)
    # Every faulted closed window names the injected fault top-1.
    for l in lines:
        if l["ranking"]:
            assert l["ranking"][0][0] == tl.fault_pod_op


def test_follow_restart_resumes_from_cursor(timeline, tmp_path):
    """A NEW follower process (fresh TableRCA) over the same out_dir
    picks up at the cursor instead of re-ranking from the start."""
    tl = timeline
    csv = tmp_path / "stream.csv"
    out = tmp_path / "out"
    w0, w1, w2, w3 = (_window_frame(tl, w) for w in range(4))
    pd.concat([w0, w1, w2]).to_csv(csv, index=False)

    rca1 = _rca(tl, tmp_path)
    n1 = run_follow(rca1, csv, out, poll_seconds=0.0, max_polls=1)
    assert n1 == 2

    # "Crash"; the file grows; a fresh process follows the same out dir.
    pd.concat([w0, w1, w2, w3]).to_csv(csv, index=False)
    rca2 = _rca(tl, tmp_path)
    n2 = run_follow(rca2, csv, out, poll_seconds=0.0, max_polls=1)
    assert n2 == 1  # only the newly closed window — no duplicates

    lines = [
        json.loads(l)
        for l in (out / "windows.jsonl").read_text().splitlines()
    ]
    ranked = [l["start"] for l in lines if l["ranking"]]
    assert len(ranked) == 3
    assert ranked == sorted(ranked)


def test_tail_tracker_incremental_read(tmp_path):
    """Byte-offset incremental parse (PR 5): read_appended feeds the
    parser only the header + complete lines appended since the last
    successful parse; a torn trailing line holds the cursor; rotation
    falls back to a full re-read."""
    import os

    from microrank_tpu.pipeline.follow import TailTracker

    path = tmp_path / "grow.csv"
    header = b"a,b\n"
    path.write_bytes(header + b"1,2\n3,4\n")
    tr = TailTracker()
    size = os.path.getsize(path)
    assert tr.observe_size(size) == "grew"
    payload, off = tr.read_appended(path, size)
    assert payload == header + b"1,2\n3,4\n" and off == size
    tr.parsed(size, offset=off)

    # Append two rows + a TORN third: only the complete rows return,
    # prefixed by the cached header; the cursor stops at the newline.
    with open(path, "ab") as f:
        f.write(b"5,6\n7,8\n9,")
    size = os.path.getsize(path)
    assert tr.observe_size(size) == "grew"
    payload, off = tr.read_appended(path, size)
    assert payload == header + b"5,6\n7,8\n"
    assert off == size - len(b"9,")
    tr.parsed(size, offset=off)

    # Nothing but the torn tail: no complete line -> None, cursor holds.
    assert tr.read_appended(path, size) is None

    # The torn line completes: exactly it returns.
    with open(path, "ab") as f:
        f.write(b"10\n")
    size = os.path.getsize(path)
    payload, off = tr.read_appended(path, size)
    assert payload == header + b"9,10\n" and off == size
    tr.parsed(size, offset=off)

    # Rotation: file replaced smaller -> cursor resets, full re-read.
    path.write_bytes(header + b"x,y\n")
    size = os.path.getsize(path)
    assert tr.observe_size(size) == "grew"  # shrank then counted grown
    assert tr.rotated and tr.parsed_offset == 0
    payload, off = tr.read_appended(path, size)
    assert payload == header + b"x,y\n" and off == size


def test_file_tail_source_parses_only_appended_bytes(tmp_path):
    """The streaming tail's ingest cost is O(appended), not O(file):
    the bytes handed to the parser across all polls stay close to
    file-size + per-poll headers, nowhere near the quadratic total a
    whole-file re-parse per poll pays."""
    from microrank_tpu.stream.sources import FileTailSource
    from microrank_tpu.testing import SyntheticConfig, generate_case

    case = generate_case(
        SyntheticConfig(n_operations=10, n_traces=60, seed=2)
    )
    df = case.normal
    csv = tmp_path / "grow.csv"
    n_chunks = 5
    chunk = len(df) // n_chunks
    df.iloc[:chunk].to_csv(csv, index=False)
    src = FileTailSource(
        csv, poll_seconds=0, max_polls=n_chunks + 1, sleep=lambda s: None
    )
    it = iter(src)
    got = [next(it)]
    for i in range(1, n_chunks):
        lo, hi = i * chunk, (i + 1) * chunk if i < n_chunks - 1 else len(df)
        df.iloc[lo:hi].to_csv(csv, mode="a", header=False, index=False)
        got.append(next(it))
    assert sum(len(g) for g in got) == len(df)
    # Each poll yielded exactly the appended rows (no re-yields).
    assert [len(g) for g in got][:-1] == [chunk] * (n_chunks - 1)


def test_follow_requires_out_dir(timeline, tmp_path):
    tl = timeline
    csv = tmp_path / "stream.csv"
    _window_frame(tl, 0).to_csv(csv, index=False)
    rca = _rca(tl, tmp_path)
    with pytest.raises(ValueError, match="out_dir"):
        next(follow_table(rca, csv, None, poll_seconds=0.0))


def test_follow_cli_flag(timeline, tmp_path):
    """`run --follow --follow-idle-exit 1` drives the same loop end to
    end through the CLI."""
    from microrank_tpu.cli.main import main

    tl = timeline
    csv = tmp_path / "stream.csv"
    out = tmp_path / "cli_out"
    normal_csv = tmp_path / "normal.csv"
    tl.normal.to_csv(normal_csv, index=False)
    pd.concat(
        [_window_frame(tl, 0), _window_frame(tl, 1)]
    ).to_csv(csv, index=False)

    rc = main(
        [
            "run",
            "--normal", str(normal_csv),
            "--abnormal", str(csv),
            "-o", str(out),
            "--follow",
            "--poll-seconds", "0",
            "--follow-idle-exit", "1",
            "--detect-minutes", str(tl.window_minutes),
            "--skip-minutes", "0",
        ]
    )
    assert rc == 0
    lines = [
        json.loads(l)
        for l in (out / "windows.jsonl").read_text().splitlines()
    ]
    assert sum(1 for l in lines if l["ranking"]) == 1  # window 0 closed
    assert (out / "cursor.json").exists()
