"""bench.py is the driver's measurement artifact — guard it against
bitrot: both modes must run end to end on CPU and emit the JSON
contract ({metric, value, unit, vs_baseline} + the timing keys)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

_REPO = Path(__file__).parent.parent


def _run_bench(extra_env):
    env = {
        **os.environ,
        "PYTHONPATH": str(_REPO),
        "JAX_PLATFORMS": "cpu",
        "BENCH_REPEATS": "1",
        "BENCH_ORACLE_SPANS": "2000",
        **extra_env,
    }
    proc = subprocess.run(
        [sys.executable, str(_REPO / "bench.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
        cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout  # ONE JSON line on stdout
    out = json.loads(lines[0])
    assert out["metric"] == "spans_per_sec_ranked"
    assert out["unit"] == "spans/s"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0
    for key in ("build_ms", "rank_ms", "staging_ms"):
        assert out[key] >= 0, key
    return out


def test_bench_single_window_mode():
    # Config 1 is 1k spans — bench.py generates and caches the case on a
    # fresh checkout in well under a second, so no cache precondition.
    _run_bench({"BENCH_CONFIG": "1"})


@pytest.mark.skipif(
    not (_REPO / "bench_data" / "tl_s250000_o2000_f60000_w8").exists(),
    reason="config-4 timeline case not cached",
)
def test_bench_batched_mode():
    # The batched (vmapped multi-window) mode, reusing the cached
    # config-4 timeline with one repeat — ~250k spans ranks in seconds
    # on CPU.
    _run_bench({"BENCH_CONFIG": "4"})
