"""Scenario matrix + self-tuning policy engine (scenarios/): generator
determinism (same seed -> byte-identical span stream), the six fault
families end to end (error-status detection, multi-culprit ground
truth, cascade hardness, drift no-alarm), the evaluation harness's
per-formula scoring record, and tuned-policy resolution — precedence
(explicit config > persisted policy > built-in default) across the
stream, serve, table, and pandas-run lanes, with stale policies
rejected WHOLE and counted. All on CPU jax.
"""

import dataclasses
import json

import pytest

from microrank_tpu.config import MicroRankConfig, RuntimeConfig, SpectrumConfig
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.scenarios import (
    FAMILIES,
    ScenarioSpec,
    default_matrix,
    generate_scenario,
    load_policy,
    profile_from_frame,
    resolve_policy,
    run_matrix,
    run_scenario,
    save_policy,
    select_policy,
    workload_digest,
)
from microrank_tpu.scenarios.policy import (
    POLICY_VERSION,
    PROFILE_SCHEMA,
    apply_tuned_policy,
)
from microrank_tpu.testing import SyntheticConfig, generate_case
from microrank_tpu.testing.synthetic import generate_timeline


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture
def policy_dir(tmp_path, monkeypatch):
    """Hermetic policy.json location for this test."""
    d = tmp_path / "policy"
    d.mkdir()
    monkeypatch.setenv("MICRORANK_POLICY_DIR", str(d))
    return d


def _small_spec(**kw):
    kw.setdefault("name", "t-latency")
    kw.setdefault("family", "latency")
    # Seed 7 is a pinned known-easy latency case: the culprit is top-1
    # exact for dstar2 (seed 5 of this shape ranks an ancestor first —
    # propagation hardness, which the matrix measures, not this test).
    kw.setdefault("seed", 7)
    kw.setdefault("n_windows", 6)
    kw.setdefault("faulted", (2,))
    kw.setdefault("n_operations", 20)
    kw.setdefault("n_traces", 150)
    kw.setdefault("n_kinds", 12)
    return ScenarioSpec(**kw)


# ------------------------------------------------------------- generator


def test_default_matrix_covers_every_family():
    specs = default_matrix(seed=3)
    assert sorted({s.family for s in specs}) == sorted(FAMILIES)
    # Seeds derive from the one matrix seed and differ per scenario.
    assert len({s.seed for s in specs}) == len(specs)
    full = default_matrix(seed=3, full=True)
    assert len(full) == 2 * len(specs)
    assert sorted({s.family for s in full}) == sorted(FAMILIES)


def test_generator_determinism_byte_identical():
    spec = _small_spec()
    d1 = workload_digest(generate_scenario(spec))
    d2 = workload_digest(generate_scenario(spec))
    assert d1 == d2
    other = workload_digest(
        generate_scenario(dataclasses.replace(spec, seed=6))
    )
    assert other != d1


def test_unknown_family_rejected():
    with pytest.raises(ValueError, match="unknown scenario family"):
        ScenarioSpec(name="x", family="quantum")


# ----------------------------------------------------------- fault families


def test_error_fault_statuscode_and_detection():
    """Error faults carry no latency signal; the status column plus the
    detect seam's error classification finds and ranks them."""
    from microrank_tpu.detect import compute_slo, detect_partition
    from microrank_tpu.rank_backends import get_backend

    cfg = MicroRankConfig()
    case = generate_case(
        SyntheticConfig(
            seed=11, fault_kind="error", n_operations=24,
            n_traces=200, n_kinds=16,
        )
    )
    assert "statusCode" in case.abnormal.columns
    vocab, slo = compute_slo(case.normal)
    flag, nrm, abn = detect_partition(cfg, vocab, slo, case.abnormal)
    assert flag and nrm and abn
    top, _ = get_backend(cfg).rank_window(case.abnormal, nrm, abn)
    assert top[0] == case.fault_pod_op
    # The same window under a status-blind detector does NOT flag:
    # error faults fail fast, so there is no latency deviation.
    blind = cfg.replace(
        detector=dataclasses.replace(
            cfg.detector, error_status_abnormal=False
        )
    )
    flag2, _, abn2 = detect_partition(blind, vocab, slo, case.abnormal)
    assert not abn2 and not flag2


def test_error_status_propagates_to_ancestors():
    tl = generate_timeline(
        SyntheticConfig(
            seed=7, fault_kind="error", n_operations=20,
            n_traces=100, n_kinds=8,
        ),
        3,
        [1],
    )
    w1 = tl.timeline[tl.timeline.traceID.str.startswith("w1x")]
    err_traces = set(w1[w1.statusCode > 0].traceID)
    assert err_traces
    roots = w1[(w1.ParentSpanId == "") & (w1.traceID.isin(err_traces))]
    assert (roots.statusCode > 0).all()
    # Clean windows carry the column but no error bit.
    w0 = tl.timeline[tl.timeline.traceID.str.startswith("w0x")]
    assert int(w0.statusCode.sum()) == 0


def test_multi_fault_truth_set_and_source():
    from microrank_tpu.stream import SyntheticSource

    src = SyntheticSource(
        n_windows=4,
        faulted=[1],
        synth_config=SyntheticConfig(
            seed=9, n_faults=2, fault_path_overlap=0.0,
            n_operations=30, n_traces=150, n_kinds=12,
        ),
    )
    assert len(set(src.fault_pod_ops)) == 2
    assert src.fault_pod_op == src.fault_pod_ops[0]


def test_drift_timeline_scales_latency():
    import numpy as np

    tl = generate_timeline(
        SyntheticConfig(
            seed=3, drift_per_window=0.2, n_operations=15, n_traces=80,
        ),
        4,
        [],
    )
    roots = [
        tl.timeline[tl.timeline.traceID.str.startswith(f"w{i}x")]
        .groupby("traceID")["duration"].max().mean()
        for i in range(4)
    ]
    assert roots[3] > roots[0] * 1.4
    assert not any(tl.window_faulted)
    assert np.isfinite(roots).all()


# --------------------------------------------------------------- harness


@pytest.fixture(scope="module")
def latency_record():
    cfg = MicroRankConfig()
    return run_scenario(
        cfg, _small_spec(), out_dir=None, stream_lane=True
    )


def test_scenario_record_scores_all_13_formulas(latency_record):
    from microrank_tpu.spectrum.formulas import METHODS

    rec = latency_record
    assert sorted(rec["formulas"]) == sorted(METHODS)
    assert len(rec["formulas"]) == 13
    fx = rec["formulas"]["dstar2"]
    assert fx["map"] == 1.0 and fx["top1_rate"] == 1.0
    assert fx["windows"] == 1  # one faulted window
    assert rec["detection"]["tp"] == 1
    assert rec["detection"]["fp"] == 0
    assert rec["truth"] and rec["profile"]


def test_scenario_record_attribution_features(latency_record):
    rec = latency_record
    attr = rec["attribution"]
    assert attr is not None
    culprit = rec["truth"][0]
    assert culprit in attr
    feats = attr[culprit]
    # PR 8's spectrum counters + PPR mass split as diagnostic features.
    assert feats["counters"]["ef"] > 0
    assert set(feats["counters"]) == {"ef", "nf", "ep", "np"}
    assert "abnormal_weight" in feats["mass"]
    assert "dstar2" in feats["terms"] and len(feats["terms"]) == 13
    assert feats["rank"] == 1


def test_scenario_stream_lane_incident(latency_record):
    s = latency_record["stream"]
    assert s["windows"] == 6
    assert s["incidents_opened"] == 1
    assert s["topc_hits"] == s["ranked_faulted"] >= 1


def test_drift_scenario_retrains_not_alarms(registry, tmp_path):
    spec = _small_spec(
        name="t-drift", family="drift", faulted=(),
        drift_per_window=0.05, n_windows=6,
    )
    rec = run_scenario(
        MicroRankConfig(), spec, out_dir=tmp_path, stream_lane=True
    )
    assert rec["truth"] == [] and rec["formulas"] == {}
    assert rec["detection"]["fp"] == 0          # never alarms
    s = rec["stream"]
    assert s["incidents_opened"] == 0
    # The online baseline absorbed the shift: its SLO center moved up.
    assert s["baseline_shift"] is not None and s["baseline_shift"] > 1.0


def test_run_matrix_artifact_and_policy(registry, tmp_path, policy_dir):
    specs = [
        _small_spec(),
        _small_spec(
            name="t-err", family="error", fault_kind="error", seed=8
        ),
    ]
    art = run_matrix(
        MicroRankConfig(),
        specs=specs,
        out_dir=tmp_path,
        seed=5,
        stream_lane=False,
        tune=False,
    )
    path = tmp_path / "scenario_matrix.json"
    assert path.exists()
    on_disk = json.loads(path.read_text())
    assert on_disk["n_scenarios"] == 2
    assert {r["family"] for r in on_disk["scenarios"]} == {
        "latency", "error",
    }
    # Policy persisted into the hermetic dir and loadable.
    data, reject = load_policy(policy_dir)
    assert reject is None and data["profiles"]
    entry = next(iter(data["profiles"].values()))
    assert entry["method"] in on_disk["scenarios"][0]["formulas"]


# ---------------------------------------------------------------- policy


def _write_policy(policy_dir, profiles: dict, version=POLICY_VERSION,
                  schema=None):
    save_policy(
        policy_dir,
        {
            "version": version,
            "profile_schema": schema or PROFILE_SCHEMA,
            "profiles": profiles,
        },
    )


def _policy_counter(reg):
    c = reg.get("microrank_policy_events_total")
    return {
        (s["labels"]["lane"], s["labels"]["outcome"]): s["value"]
        for s in c.samples()
    }


def test_policy_precedence_explicit_config_wins(registry, policy_dir):
    case = generate_case(
        SyntheticConfig(seed=7, n_operations=24, n_traces=120, n_kinds=16)
    )
    prof = profile_from_frame(case.normal)
    _write_policy(
        policy_dir,
        {
            prof.key(): {
                "method": "ochiai", "kernel": "pcsr",
                "pad_policy": "pow2",
            }
        },
    )
    # No explicit overrides: all three fields come from the policy.
    cfg, res = apply_tuned_policy(
        MicroRankConfig(), lane="stream", profile_frame=case.normal
    )
    assert res.outcome == "applied"
    assert cfg.spectrum.method == "ochiai"
    assert cfg.runtime.kernel == "pcsr"
    assert cfg.runtime.pad_policy == "pow2"
    # Explicit method: config wins that field, policy keeps the rest.
    base = MicroRankConfig().replace(
        spectrum=SpectrumConfig(method="dice")
    )
    cfg2, res2 = apply_tuned_policy(
        base, lane="stream", profile_frame=case.normal
    )
    assert cfg2.spectrum.method == "dice"
    assert res2.fields["method"]["source"] == "config"
    assert cfg2.runtime.kernel == "pcsr"
    assert res2.fields["kernel"]["source"] == "policy"
    # tuned_policy="off" pins built-in defaults entirely.
    off = MicroRankConfig().replace(
        runtime=dataclasses.replace(
            RuntimeConfig(), tuned_policy="off"
        )
    )
    cfg3, res3 = apply_tuned_policy(
        off, lane="stream", profile_frame=case.normal
    )
    assert res3.outcome == "disabled"
    assert cfg3.spectrum.method == SpectrumConfig().method


def test_stale_policy_rejected_whole(registry, policy_dir):
    """Version or profile mismatch rejects the WHOLE policy (cold start
    on built-in defaults) and counts outcome=rejected — the checkpoint
    whole-rejection rule, mirrored."""
    case = generate_case(
        SyntheticConfig(seed=7, n_operations=24, n_traces=120, n_kinds=16)
    )
    # (a) schema-version mismatch.
    _write_policy(policy_dir, {}, version=POLICY_VERSION + 1)
    cfg, res = apply_tuned_policy(
        MicroRankConfig(), lane="stream", profile_frame=case.normal
    )
    assert res.outcome == "rejected" and "version" in res.reason
    assert cfg.spectrum.method == SpectrumConfig().method
    # (b) profile-bucket schema mismatch.
    bad_schema = dict(PROFILE_SCHEMA)
    bad_schema["span_volume"] = [1, 2]
    _write_policy(policy_dir, {}, schema=bad_schema)
    _, res = apply_tuned_policy(
        MicroRankConfig(), lane="serve", profile_frame=case.normal
    )
    assert res.outcome == "rejected"
    # (c) workload-profile mismatch: tuned for a different workload.
    _write_policy(
        policy_dir,
        {"spans=large|ops=large|dedup=low": {"method": "ochiai"}},
    )
    _, res = apply_tuned_policy(
        MicroRankConfig(), lane="table", profile_frame=case.normal
    )
    assert res.outcome == "rejected"
    # (d) corrupt JSON.
    (policy_dir / "policy.json").write_text("{not json")
    _, res = apply_tuned_policy(
        MicroRankConfig(), lane="run", profile_frame=case.normal
    )
    assert res.outcome == "rejected"
    counts = _policy_counter(registry)
    assert counts[("stream", "rejected")] == 1
    assert counts[("serve", "rejected")] == 1
    assert counts[("table", "rejected")] == 1
    assert counts[("run", "rejected")] == 1


# ------------------------------------------------- lane resolution e2e


def _tuned_policy_for(policy_dir, frame, **fields):
    prof = profile_from_frame(frame)
    entry = {"method": "ochiai", "kernel": "packed",
             "pad_policy": "pow2q"}
    entry.update(fields)
    _write_policy(policy_dir, {prof.key(): entry})
    return prof


def test_stream_lane_consults_policy(registry, policy_dir, tmp_path):
    from microrank_tpu.obs import read_journal
    from microrank_tpu.stream import StreamEngine, SyntheticSource

    src = SyntheticSource(
        n_windows=4,
        faulted=[2],
        synth_config=SyntheticConfig(
            n_operations=24, n_traces=200, n_kinds=16, seed=5
        ),
    )
    _tuned_policy_for(policy_dir, src.normal)
    eng = StreamEngine(MicroRankConfig(), src, out_dir=tmp_path)
    assert eng.config.spectrum.method == "ochiai"
    assert eng.policy_resolution.outcome == "applied"
    s = eng.run()
    assert s.ranked == 1 and s.incidents_opened == 1
    jev = read_journal(tmp_path / "journal.jsonl")
    pol = [e for e in jev if e["event"] == "policy"]
    assert len(pol) == 1
    assert pol[0]["outcome"] == "applied"
    assert pol[0]["method"] == "ochiai"
    assert pol[0]["method_source"] == "policy"
    assert _policy_counter(registry)[("stream", "applied")] == 1


def test_stream_lane_explicit_override_wins(registry, policy_dir, tmp_path):
    from microrank_tpu.stream import StreamEngine, SyntheticSource

    src = SyntheticSource(
        n_windows=3,
        faulted=[],
        synth_config=SyntheticConfig(
            n_operations=24, n_traces=150, n_kinds=16, seed=5
        ),
    )
    _tuned_policy_for(policy_dir, src.normal)
    explicit = MicroRankConfig().replace(
        spectrum=SpectrumConfig(method="jaccard")
    )
    eng = StreamEngine(explicit, src, out_dir=tmp_path)
    assert eng.config.spectrum.method == "jaccard"
    assert eng.policy_resolution.fields["method"]["source"] == "config"
    assert eng.policy_resolution.fields["kernel"]["source"] == "policy"


def test_serve_lane_consults_policy(registry, policy_dir, tmp_path):
    from microrank_tpu.serve import ServeService

    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    _tuned_policy_for(policy_dir, case.normal)
    service = ServeService(MicroRankConfig(), out_dir=tmp_path)
    try:
        service.fit_baseline(case.normal)
        assert service.config.spectrum.method == "ochiai"
        # The batcher and router see the tuned config too (they were
        # constructed before fit_baseline resolved it).
        assert service.scheduler.batcher.config.spectrum.method == "ochiai"
        assert service.router.config.spectrum.method == "ochiai"
        assert service.policy_resolution.outcome == "applied"
        assert _policy_counter(registry)[("serve", "applied")] == 1
    finally:
        service.shutdown(drain=False)


def test_table_lane_consults_policy(registry, policy_dir, tmp_path):
    from microrank_tpu import native
    from microrank_tpu.pipeline import TableRCA
    from microrank_tpu.scenarios import profile_from_counts

    if not native.native_available():
        pytest.skip("native engine unavailable")
    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    csv = tmp_path / "normal.csv"
    case.normal.to_csv(csv, index=False)
    table = native.load_span_table(csv, cache=False)
    # The table lane profiles from counts (dedup unknown -> "low").
    names = (
        case.normal["serviceName"].astype(str)
        + "_"
        + case.normal["operationName"].astype(str)
    )
    prof = profile_from_counts(len(case.normal), int(names.nunique()))
    _write_policy(
        policy_dir,
        {prof.key(): {"method": "ochiai", "kernel": "packed",
                      "pad_policy": "pow2q"}},
    )
    rca = TableRCA(MicroRankConfig())
    rca.fit_baseline(table)
    assert rca.config.spectrum.method == "ochiai"
    assert rca.policy_resolution.outcome == "applied"
    assert _policy_counter(registry)[("table", "applied")] == 1


def test_run_lane_consults_policy(registry, policy_dir):
    from microrank_tpu.pipeline import OnlineRCA

    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    _tuned_policy_for(policy_dir, case.normal)
    rca = OnlineRCA(MicroRankConfig())
    rca.fit_baseline(case.normal)
    assert rca.config.spectrum.method == "ochiai"
    assert rca.backend.config.spectrum.method == "ochiai"
    assert rca.policy_resolution.outcome == "applied"


# ------------------------------------------------------------- selection


def test_select_policy_best_map_wins_deterministically():
    records = [
        {
            "profile": "spans=small|ops=small|dedup=high",
            "formulas": {
                "dstar2": {"map": 0.5, "top1_rate": 0.5, "mrr": 0.5},
                "ochiai": {"map": 0.9, "top1_rate": 1.0, "mrr": 1.0},
            },
        },
        {
            "profile": "spans=small|ops=small|dedup=high",
            "formulas": {
                "dstar2": {"map": 0.7, "top1_rate": 1.0, "mrr": 1.0},
                "ochiai": {"map": 0.9, "top1_rate": 1.0, "mrr": 1.0},
            },
        },
    ]
    pol = select_policy(records, matrix_seed=3)
    entry = pol["profiles"]["spans=small|ops=small|dedup=high"]
    assert entry["method"] == "ochiai"           # mean MAP 0.9 vs 0.6
    assert entry["evidence"]["scenarios"] == 2
    assert pol["version"] == POLICY_VERSION
    assert pol["profile_schema"] == PROFILE_SCHEMA
    # Ties break by name (deterministic): equal stats -> alphabetical.
    tie = [
        {
            "profile": "p",
            "formulas": {
                "m2": {"map": 0.5, "top1_rate": 0.5, "mrr": 0.5},
                "dice": {"map": 0.5, "top1_rate": 0.5, "mrr": 0.5},
            },
        }
    ]
    assert select_policy(tie)["profiles"]["p"]["method"] == "dice"


def test_select_policy_timing_sweep_fields():
    records = [
        {
            "profile": "p",
            "formulas": {"dstar2": {"map": 1.0, "top1_rate": 1.0,
                                    "mrr": 1.0}},
        }
    ]
    timings = {
        "p": {"kernel": "pcsr", "pad_policy": "pow2", "rank_ms": 1.5,
              "candidates": {}}
    }
    entry = select_policy(records, timings)["profiles"]["p"]
    assert entry["kernel"] == "pcsr"
    assert entry["pad_policy"] == "pow2"
    assert entry["evidence"]["rank_ms"] == 1.5
