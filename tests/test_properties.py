"""Property tests (SURVEY.md §4 item 5): permutation/scale invariance,
determinism, numeric hygiene."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import partition_case
from microrank_tpu.config import MicroRankConfig
from microrank_tpu.graph import build_window_graph
from microrank_tpu.rank_backends.jax_tpu import rank_window_device
from microrank_tpu.testing import SyntheticConfig, generate_case


@pytest.fixture(scope="module")
def ranked_case():
    case = generate_case(
        SyntheticConfig(n_operations=20, n_traces=120, seed=2,
                        n_kinds=24, child_keep_prob=0.6)
    )
    nrm, abn = partition_case(case)
    assert nrm and abn
    return case, nrm, abn


def _rank(case, nrm, abn, kernel="coo", df=None):
    cfg = MicroRankConfig()
    graph, names, _, _ = build_window_graph(
        case.abnormal if df is None else df, nrm, abn
    )
    ti, ts, nv = rank_window_device(
        jax.tree.map(jnp.asarray, graph), cfg.pagerank, cfg.spectrum, None,
        kernel,
    )
    n = int(nv)
    return (
        [names[int(i)] for i in np.asarray(ti)[:n]],
        np.asarray(ts)[:n],
    )


def test_row_permutation_invariance(ranked_case):
    # Shuffling span rows must not change the ranking.
    case, nrm, abn = ranked_case
    top_a, sc_a = _rank(case, nrm, abn)
    rng = np.random.default_rng(0)
    shuffled = case.abnormal.sample(frac=1.0, random_state=7).reset_index(
        drop=True
    )
    top_b, sc_b = _rank(case, nrm, abn, df=shuffled)
    assert top_a == top_b
    np.testing.assert_allclose(sc_a, sc_b, rtol=1e-5)


def test_partition_order_invariance(ranked_case):
    # The order of trace ids inside each partition list is irrelevant.
    case, nrm, abn = ranked_case
    top_a, _ = _rank(case, nrm, abn)
    top_b, _ = _rank(case, list(reversed(nrm)), list(reversed(abn)))
    assert top_a == top_b


def test_determinism_across_runs(ranked_case):
    case, nrm, abn = ranked_case
    top_a, sc_a = _rank(case, nrm, abn)
    top_b, sc_b = _rank(case, nrm, abn)
    assert top_a == top_b
    np.testing.assert_array_equal(sc_a, sc_b)


def test_bf16_rank_parity(ranked_case):
    # bf16 matmuls must preserve the ranking ORDER (scores may drift).
    case, nrm, abn = ranked_case
    top_f32, _ = _rank(case, nrm, abn, kernel="dense")
    top_bf16, _ = _rank(case, nrm, abn, kernel="dense_bf16")
    assert top_f32[0] == top_bf16[0]
    # Allow adjacent swaps deep in the tail but not set changes.
    assert set(top_f32) == set(top_bf16)
    assert top_f32[:3] == top_bf16[:3]


def test_scores_finite_and_positive(ranked_case):
    case, nrm, abn = ranked_case
    _, sc = _rank(case, nrm, abn)
    assert np.isfinite(sc).all()
    assert (sc >= 0).all()


def test_duration_scale_changes_detection_not_build(ranked_case):
    # Scaling all durations by a constant leaves the PageRank graphs
    # untouched (they depend only on structure) — the rescale invariance
    # of pagerank.py:107 generalized.
    case, nrm, abn = ranked_case
    df = case.abnormal.copy()
    df["duration"] = df["duration"] * 2
    top_a, sc_a = _rank(case, nrm, abn)
    top_b, sc_b = _rank(case, nrm, abn, df=df)
    assert top_a == top_b
    np.testing.assert_allclose(sc_a, sc_b, rtol=1e-6)


def test_numerics_guard():
    from microrank_tpu.utils.guards import NumericsError, assert_finite_scores

    assert_finite_scores([1.0, 2.0], "t")  # fine
    with pytest.raises(NumericsError, match="non-finite"):
        assert_finite_scores([1.0, float("nan")], "t")
    with pytest.raises(NumericsError):
        assert_finite_scores([float("inf")], "t")


def test_row_order_invariance(small_case):
    # With the name-sorted vocab and pinned tie order, the FULL ranking
    # (names and positions, not just scores) is invariant to the order
    # spans arrive in — previously the vocab followed first appearance
    # and exact ties followed it.
    from microrank_tpu.rank_backends import get_backend

    nrm, abn = partition_case(small_case)
    cfg = MicroRankConfig()
    base_top, base_sc = get_backend(cfg).rank_window(
        small_case.abnormal, nrm, abn
    )
    for seed in (0, 1):
        shuffled = small_case.abnormal.sample(
            frac=1.0, random_state=seed
        ).reset_index(drop=True)
        top, sc = get_backend(cfg).rank_window(shuffled, nrm, abn)
        assert top == base_top, seed
        assert np.allclose(sc, base_sc, rtol=1e-6)
