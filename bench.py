"""Benchmark harness: rank one large trace window on the device backend.

Prints ONE JSON line:
    {"metric": "spans_per_sec_ranked", "value": N, "unit": "spans/s",
     "vs_baseline": R}

* value — spans of the abnormal window ranked per second of wall-clock
  through the device path (host COO graph build + jitted rank program +
  device->host fetch of the top-k result, post-compile; median of
  BENCH_REPEATS runs). The fetch is deliberate: on the tunneled TPU
  platform jax.block_until_ready does not wait for execution, so only a
  value transfer is a sound timing fence.
* vs_baseline — speedup of that spans/s over the faithful numpy oracle
  backend measured on a trace-subsample of the same window (the oracle is
  the reference's dense-matrix semantics; its cost is superlinear, so the
  subsample keeps the baseline measurable — the ratio therefore
  *understates* the real speedup at full scale).

Pipeline benched is the native lane: C++ mmap ingest (interned arrays) ->
int-only window build -> jitted rank. Synthetic chaos-case CSVs are
generated once and cached under bench_data/.

Config via env: BENCH_CONFIG=1..7 selects a workload preset
(BASELINE.json's five plus the 4M stretch and the 16M ceiling probe;
default 5 = 1M spans / 5k ops); BENCH_SPANS / BENCH_OPS override the
preset's sizes; BENCH_REPEATS (5), BENCH_ORACLE_SPANS (20_000),
BENCH_KERNEL
(auto|kind|packed|packed_bf16|packed_blocked|csr|coo|dense|dense_bf16|
pallas; "kind" = the kind-compressed reduced-precision kernel, which
"auto" selects itself once the window's measured dedup factor clears
the threshold — the artifact records the factor as "kind_dedup" and
the differenced-profile ratio as "speedup_kind_vs_packed"),
BENCH_FAULT_MS (60000), BENCH_BATCH (preset-dependent; 1 disables),
Host->device staging is part of the headline value BY DEFAULT (round 4
on; BENCH_TIME_STAGING=0 excludes it to reproduce the r1-r3
staging-excluded methodology; it is always measured and reported as
"staging_ms" either way; both modes stage once outside the repeat loop,
at the same pipeline boundary). BENCH_BLOB=0 replaces the default
single-buffer blob staging (one transfer) with per-leaf device_put
(~50 RPC round trips on the tunneled runtime). Replay presets also run
the adaptive-router replay (PR 5; BENCH_ROUTER=0 skips): group-wise
dispatch through dispatch.DispatchRouter with double-buffered staging —
the artifact gains "route" (vmapped/sharded), "overlap_ms" (staging
hidden behind rank) and a "router" block with ms/window. Every run also
benches the GIANT-WINDOW tier (PR 6; BENCH_GIANT=0 skips,
BENCH_GIANT_SPANS/BENCH_GIANT_OPS size it): a ~10M-span synthetic
window past the DEFAULT bitmap budget, ranked by the partition-centric
pcsr kernel AND the legacy csr fallback — the artifact's "giant" block
records per-kernel ms_per_iter, staged HBM footprints, the would-be
bitmap bytes, tie-aware oracle parity, and speedup_pcsr_vs_csr.
Details go to stderr; stdout carries only the JSON line.

Reference baseline context: the reference's PageRank Scorer takes 5.5 s
per window of ~1e2 ops / 1e2-1e3 traces on a CPU core (paper Table 7;
BASELINE.md) — the target here is a window 3-4 orders of magnitude larger
in under a second (BASELINE.json north star).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


_SENTINEL = None


def _host_sentinel():
    """Process-wide contention sentinel (obs.host): loadavg + CPU-steal
    sampling so a bench artifact recorded on a contended host carries
    its own asterisk (round 5's replay was silently 1.7x slower in the
    artifact of record because host contention was invisible)."""
    global _SENTINEL
    if _SENTINEL is None:
        from microrank_tpu.obs.host import ContentionSentinel

        _SENTINEL = ContentionSentinel()
        _SENTINEL.sample()  # arm the steal differencing
    return _SENTINEL


def _host_fields(start_sample, end_sample) -> dict:
    """The artifact's self-flagging host block: both samples plus a
    headline `contended` bool (either end saw load/steal pressure)."""
    contended = bool(
        start_sample.get("contended") or end_sample.get("contended")
    )
    if contended:
        log(
            "WARNING: host contended during the bench "
            f"(start {start_sample}, end {end_sample}) — treat the "
            "headline as a lower bound"
        )
    return {
        "host": {
            "start": start_sample,
            "end": end_sample,
            "contended": contended,
        }
    }


def _ensure_data(spans_target, n_ops, fault_ms):
    """Generate (or reuse) the cached chaos-case CSV pair."""
    root = Path(__file__).parent / "bench_data"
    case_dir = root / f"s{spans_target}_o{n_ops}_f{int(fault_ms)}"
    truth_path = case_dir / "ground_truth.json"
    if truth_path.exists():
        truth = json.loads(truth_path.read_text())
        return case_dir, truth
    from microrank_tpu.testing import (
        SyntheticConfig,
        generate_case_with_spans,
    )

    t0 = time.perf_counter()
    case = generate_case_with_spans(
        SyntheticConfig(
            n_operations=n_ops,
            n_kinds=max(32, n_ops // 50),
            child_keep_prob=0.55,
            fault_latency_ms=fault_ms,
            seed=0,
        ),
        target_spans=spans_target,
    )
    case_dir.mkdir(parents=True, exist_ok=True)
    case.normal.to_csv(case_dir / "normal.csv", index=False)
    case.abnormal.to_csv(case_dir / "abnormal.csv", index=False)
    truth = {
        "fault_pod_op": case.fault_pod_op,
        "n_abnormal_spans": len(case.abnormal),
    }
    truth_path.write_text(json.dumps(truth))
    log(
        f"generated + cached case in {time.perf_counter() - t0:.1f}s "
        f"({len(case.abnormal)} abnormal spans) -> {case_dir}"
    )
    return case_dir, truth


# Workload presets, selectable via BENCH_CONFIG=1..7
# (BENCH_SPANS / BENCH_OPS still override individually). Config 4 is the
# "batched multi-window spectrum (8 windows vmapped)" preset: the window
# is time-sliced into `batch` sub-windows, each detected/partitioned
# separately, and ONE vmapped device program ranks them all
# (BENCH_BATCH overrides; BENCH_BATCH=1 on any config disables).
CONFIG_PRESETS = {
    "1": dict(spans=1_000, ops=40),        # Bookinfo-scale replay
    "2": dict(spans=10_000, ops=500),      # synthetic Erdős–Rényi
    "3": dict(spans=50_000, ops=1_000),    # Online-Boutique scale
    "4": dict(spans=250_000, ops=2_000, batch=8),  # TrainTicket, vmapped
    "5": dict(spans=1_000_000, ops=5_000, replay=8),  # sharded-mesh target
    "6": dict(spans=4_000_000, ops=10_000),  # stretch (EVALUATION.md row)
    "7": dict(spans=16_000_000, ops=16_000),  # 16M-span ceiling probe
}


def _ensure_batch_data(spans_target, n_ops, fault_ms, n_batch):
    """Generate (or reuse) a cached n_batch-window faulted timeline."""
    root = Path(__file__).parent / "bench_data"
    case_dir = root / f"tl_s{spans_target}_o{n_ops}_f{int(fault_ms)}_w{n_batch}"
    truth_path = case_dir / "ground_truth.json"
    if truth_path.exists():
        return case_dir, json.loads(truth_path.read_text())
    from microrank_tpu.testing import SyntheticConfig
    from microrank_tpu.testing.synthetic import generate_timeline_with_spans

    t0 = time.perf_counter()
    tl = generate_timeline_with_spans(
        SyntheticConfig(
            n_operations=n_ops,
            n_kinds=max(32, n_ops // 50),
            child_keep_prob=0.55,
            fault_latency_ms=fault_ms,
            seed=0,
        ),
        spans_target // n_batch,
        n_batch,
        list(range(n_batch)),  # every window carries the fault
    )
    case_dir.mkdir(parents=True, exist_ok=True)
    tl.normal.to_csv(case_dir / "normal.csv", index=False)
    tl.timeline.to_csv(case_dir / "abnormal.csv", index=False)
    truth = {
        "fault_pod_op": tl.fault_pod_op,
        "n_abnormal_spans": len(tl.timeline),
        "start_us": int(tl.start.value // 1000),
        "window_minutes": tl.window_minutes,
    }
    truth_path.write_text(json.dumps(truth))
    log(
        f"generated + cached {n_batch}-window timeline in "
        f"{time.perf_counter() - t0:.1f}s ({len(tl.timeline)} spans) "
        f"-> {case_dir}"
    )
    return case_dir, truth


def _collapse_mode() -> str:
    """Trace-kind collapse at graph build (BENCH_COLLAPSE=auto|on|off;
    default auto — RuntimeConfig.collapse_kinds' default). Exactness is
    re-checked every run: the full-window float64 oracle ranks an
    UNCOLLAPSED build of the same window."""
    mode = os.environ.get("BENCH_COLLAPSE", "auto")
    if mode not in ("auto", "on", "off"):
        log(f"unknown BENCH_COLLAPSE={mode!r}; using 'auto'")
        return "auto"
    return mode


def _prefer_bf16() -> bool:
    """auto-kernel bf16 preference (BENCH_BF16=0 restores f32 packed —
    RuntimeConfig.prefer_bf16's default is on)."""
    return os.environ.get("BENCH_BF16", "1") != "0"


def _time_staging() -> bool:
    """Staging is part of the headline by default (the honest end-to-end
    number — VERDICT r3 #2/#3); BENCH_TIME_STAGING=0 excludes it to
    reproduce the r1-r3 methodology."""
    return os.environ.get("BENCH_TIME_STAGING", "1") != "0"


def _use_blob() -> bool:
    """Single-buffer staging (rank_backends.blob): one transfer instead
    of ~50, so staging stops paying ~50 RPC round trips on the tunneled
    runtime. BENCH_BLOB=0 restores per-leaf device_put."""
    return os.environ.get("BENCH_BLOB", "1") != "0"


def _enable_compile_cache() -> None:
    """Persist compiled XLA programs across bench invocations (same
    cache the CLI wires up — the driver re-runs this script cold every
    round, and the big fused rank program costs tens of seconds to
    compile but milliseconds to reload). BENCH_COLD_COMPILE=1 skips the
    cache to measure a true cold compile."""
    if os.environ.get("BENCH_COLD_COMPILE") == "1":
        log("compile cache: disabled (BENCH_COLD_COMPILE=1)")
        return
    from microrank_tpu.cli.main import _enable_jit_cache

    _enable_jit_cache()
    import jax

    log(f"compile cache: {jax.config.jax_compilation_cache_dir}")


def _stage_once(graph, kernel):
    """Stage a (possibly stacked) window graph on device ONCE — the
    shared pipeline boundary both bench modes time at. Returns
    (handle, n_bytes, stage_s); pass the handle to _rank_call /
    _rank_batched_call. Default path packs the whole graph into ONE
    uint32 buffer (rank_backends.blob) so staging is one transfer — the
    r3 number (5 MB in 1,675 ms) was ~50 per-leaf RPC round trips, not
    bandwidth."""
    import jax
    import numpy as np

    from microrank_tpu.rank_backends.jax_tpu import device_subset

    sub = device_subset(graph, kernel)
    n_bytes = sum(np.asarray(x).nbytes for x in jax.tree.leaves(sub))
    if _use_blob():
        from microrank_tpu.rank_backends.blob import pack_graph_blob

        # Timer covers the host pack memcpy too — it is a cost the blob
        # path adds, so excluding it would bias the blob-vs-per-leaf
        # comparison.
        t0 = time.perf_counter()
        blob, layout = pack_graph_blob(sub)
        blob_dev = jax.device_put(blob)
        jax.block_until_ready(blob_dev)
        stage_s = time.perf_counter() - t0
        log(
            f"device staging [blob]: {n_bytes / 1e6:.1f} MB "
            f"(pack + 1 transfer) in {stage_s:.2f}s"
        )
        return ("blob", blob_dev, layout), n_bytes, stage_s
    t0 = time.perf_counter()
    device_graph = jax.device_put(sub)  # per-leaf transfers; each pays a
    # full RPC round trip on the tunneled runtime
    jax.block_until_ready(device_graph)
    stage_s = time.perf_counter() - t0
    log(f"device staging: {n_bytes / 1e6:.1f} MB in {stage_s:.2f}s")
    return ("tree", device_graph, None), n_bytes, stage_s


def _rank_call(handle, pagerank_cfg, spectrum_cfg, kernel):
    """Dispatch the single-window rank program on a _stage_once handle."""
    from microrank_tpu.rank_backends.blob import rank_window_blob_device
    from microrank_tpu.rank_backends.jax_tpu import rank_window_device

    mode, dev, layout = handle
    if mode == "blob":
        return rank_window_blob_device(
            dev, layout, pagerank_cfg, spectrum_cfg, None, kernel
        )
    return rank_window_device(dev, pagerank_cfg, spectrum_cfg, None, kernel)


def _rank_batched_call(handle, pagerank_cfg, spectrum_cfg, kernel):
    """Dispatch the vmapped batch rank program on a _stage_once handle."""
    from microrank_tpu.parallel import rank_windows_batched
    from microrank_tpu.rank_backends.blob import (
        rank_windows_batched_blob_device,
    )

    mode, dev, layout = handle
    if mode == "blob":
        return rank_windows_batched_blob_device(
            dev, layout, pagerank_cfg, spectrum_cfg, kernel
        )
    return rank_windows_batched(dev, pagerank_cfg, spectrum_cfg, kernel)


# v5e single-chip peaks (overridable for other parts): HBM ~819 GB/s,
# MXU ~197 TFLOP/s bf16 (f32 matmuls run the MXU at roughly half that).
HBM_PEAK_GBPS = float(os.environ.get("BENCH_HBM_PEAK_GBPS", 819.0))
MXU_PEAK_TFLOPS = float(os.environ.get("BENCH_MXU_PEAK_TFLOPS", 197.0))


def _analytic_iter_cost(graph, kernel):
    """(flops, hbm_bytes) for ONE fused power-iteration step over BOTH
    partitions — the loop body's steady-state traffic model (DESIGN.md
    "Device time and utilization" derives and caveats these):

    * packed/packed_bf16: XLA fuses the shift/mask bit-unpack into each
      matvec's operand read (materialized dense matrices would need
      ~1.1 GB/iter at config 5 — 2.8x HBM peak at the measured slope,
      physically impossible; and bf16 matching f32 confirms matrix
      element bytes are not streamed). HBM traffic per step is the
      PACKED bits, read once per matvec that uses them: cov bits twice
      (forward + transposed), ss bits once. MXU work is still the dense
      shape: flops = 2·(2·Vp·Tp) + 2·Vp·Vp per partition.
    * csr: three scatter-free SpMVs touch each entry a constant number
      of times: indices + vals + gathered operand + prefix-sum
      read/write ≈ 20 B and ~4 flops per entry.
    """
    flops = 0.0
    bytes_ = 0.0
    for p in (graph.normal, graph.abnormal):
        vp = int(p.cov_unique.shape[-1] if p.cov_unique.ndim > 1
                 else p.cov_unique.shape[0])
        tp = int(p.kind.shape[-1] if p.kind.ndim > 1 else p.kind.shape[0])
        if kernel in ("packed", "packed_bf16", "packed_blocked"):
            # packed_blocked streams the same packed bytes per iteration
            # (one unpack per column block, both directions share it);
            # the model is identical — measured deltas are scan overhead.
            cov_bytes = float(vp * (tp // 8))
            # ss_stage="edges" staging strips the host ss bitmap; the
            # device-built packed array the loop streams has the same
            # ceil(V/8) byte columns.
            ss8 = int(p.ss_bits.shape[-1]) or (vp + 7) // 8
            ss_bytes = float(vp * ss8)
            vp_ss = ss8 * 8
            flops += 4.0 * vp * tp + 2.0 * vp * vp_ss
            bytes_ += 2.0 * cov_bytes + ss_bytes
        elif kernel == "kind":
            # Kind-compressed: the int8 pattern streams once per matvec
            # direction (1 byte/cell, NO unpack arithmetic — the whole
            # point), the kind axis is the collapsed width, and the
            # call-graph term is an O(C) row-sum (~20 B and ~4 flops
            # per edge like the csr model) instead of V^2 cells.
            c = int(p.ss_child.shape[-1]) or int(p.ss_val.shape[-1])
            flops += 4.0 * vp * tp + 4.0 * c
            bytes_ += 2.0 * float(vp * tp) + 20.0 * c
        elif kernel == "csr":
            e = int(p.inc_op.shape[-1])
            c = int(p.ss_child.shape[-1])
            flops += 4.0 * (2.0 * e + c)
            bytes_ += 20.0 * (2.0 * e + c)
        elif kernel == "pcsr":
            # Partition-centric streaming: each binned entry is visited
            # once per direction (indices + vals + small-range gathered
            # operand + segment-sum write ≈ 20 B, ~4 flops, like csr —
            # the win is that the operand reads are CONTIGUOUS slices /
            # small ranges instead of T-range random gathers), plus one
            # streamed pass over the trace-axis slabs per direction.
            e = int(
                p.pc_trace.shape[-2] * p.pc_trace.shape[-1]
                + p.pc_ell_op.shape[-2] * p.pc_ell_op.shape[-1]
            )
            c = int(p.ss_child.shape[-1])
            flops += 4.0 * (e + c)
            bytes_ += 16.0 * (e + c) + 8.0 * tp
        else:
            raise ValueError(f"no analytic model for kernel {kernel!r}")
    return flops, bytes_


def _tie_aware_topk_parity(
    names_a, scores_a, names_b, scores_b, k: int, rtol: float = 1e-3
) -> bool:
    """Positional top-k agreement, ties may permute — the ONE shared
    comparator (microrank_tpu.utils.ranking_compare; the dryrun gate
    uses the same function)."""
    from microrank_tpu.utils.ranking_compare import tie_aware_topk_agreement

    ok, _ = tie_aware_topk_agreement(
        names_a, scores_a, names_b, scores_b, k, rtol
    )
    return ok


def _fault_top1_hit(ranking, fault_pod_op: str) -> bool:
    """Tie-aware fault-top-1 over a WindowResult-style ranking (the
    shared evaluation helper — an exact tie at rank 1 still hits)."""
    from microrank_tpu.evaluation import topk_exact

    if not ranking:
        return False
    names = [n for n, _ in ranking]
    scores = [s for _, s in ranking]
    return topk_exact(names, scores, [fault_pod_op], k=1)


def _time_median(fn, repeats: int) -> float:
    """Median wall-clock of fn() over a clamped repeat count — the one
    timing loop every kernel measurement shares (the fn must end in a
    device->host fetch; see the timing-fence note in main())."""
    import numpy as np

    times = []
    for _ in range(max(3, min(repeats, 5))):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def _profile_device_time(
    run_at_iters, base_iters: int, t_lo: float, graph, kernel: str,
    repeats: int, extra: int | None = None,
):
    """Isolate device compute from the ~100 ms tunnel RPC floor: time
    the same program with (base + BENCH_PROFILE_EXTRA) loop iterations
    and difference — everything except the loop body (RPC, staging-free
    setup, spectrum, sort, fetch) is constant w.r.t. the trip count.
    (This assumes runtime is linear in the trip count — callers must not
    profile with a convergence tol configured, where the while_loop
    stops early regardless of the cap.)

    ``run_at_iters(n)`` runs + fetches the program with an n-step loop;
    ``t_lo`` is the already-measured median at ``base_iters``.
    """
    if extra is None:
        extra = int(os.environ.get("BENCH_PROFILE_EXTRA", 250))
    # The difference must clear the host/RPC timing noise (~±10 ms on
    # the tunnel) or the slope is garbage — keep raising the extra trip
    # count until the delta is comfortably above it.
    t_hi = t_lo
    noisy = False
    while True:
        hi = base_iters + extra
        run_at_iters(hi)  # compile outside the timed loop
        t_hi = _time_median(lambda: run_at_iters(hi), repeats)
        if t_hi - t_lo > 0.04:
            break
        if extra >= 16_000:
            noisy = True
            log(
                "  WARNING: delta never cleared the noise floor at "
                f"{extra} extra iterations; profile marked unreliable"
            )
            break
        extra *= 4
        log(
            f"  delta {t_hi - t_lo:+.4f}s below noise floor; "
            f"retrying with {extra} extra iterations"
        )
    per_iter_s = max(t_hi - t_lo, 1e-9) / extra
    flops, bytes_ = _analytic_iter_cost(graph, kernel)
    device_s = per_iter_s * base_iters
    bw = bytes_ / per_iter_s
    from microrank_tpu.obs.metrics import record_kernel_ms_per_iter

    # Wire the differenced per-iter device time into the registry gauge
    # (microrank_kernel_ms_per_iter{kernel=...}) so a bench run leaves
    # the measurement scrapeable next to the pipeline counters.
    record_kernel_ms_per_iter(kernel, per_iter_s * 1e3)
    prof = {
        "device_ms": round(device_s * 1e3, 2),
        "per_iter_us": round(per_iter_s * 1e6, 1),
        "iter_gflops": round(flops / 1e9, 2),
        "iter_mbytes": round(bytes_ / 1e6, 1),
        "hbm_gbps": round(bw / 1e9, 1),
        "hbm_util": round(bw / (HBM_PEAK_GBPS * 1e9), 3),
        "mfu": round(flops / per_iter_s / (MXU_PEAK_TFLOPS * 1e12), 4),
    }
    if noisy:
        prof["below_noise_floor"] = True
    log(
        f"device profile [{kernel}]: {prof['per_iter_us']:.0f} us/iter "
        f"({base_iters} iters = {prof['device_ms']:.1f} ms device), "
        f"{prof['iter_mbytes']:.0f} MB/iter -> {prof['hbm_gbps']:.0f} GB/s "
        f"({prof['hbm_util']:.0%} of HBM peak), MFU {prof['mfu']:.2%}"
    )
    return prof


def _oracle_subsample(
    cfg, sub_df, trace_names, nrm_codes, abn_codes, window_spans, oracle_spans
):
    """Time the numpy oracle on a trace subsample of one window — the
    shared vs_baseline methodology of both bench modes. ``sub_df`` holds
    the window's spans (pandas); returns (oracle_sps, sub_df_subsample,
    sub_nrm_names, sub_abn_names, oracle_top).
    """
    from microrank_tpu.rank_backends import NumpyRefBackend

    n_traces = len(nrm_codes) + len(abn_codes)
    per_trace = max(1, window_spans // max(n_traces, 1))
    n_take = max(2, oracle_spans // per_trace)
    sub_nrm = [trace_names[c] for c in nrm_codes[: max(2, n_take // 2)]]
    sub_abn = [trace_names[c] for c in abn_codes[: max(2, n_take // 2)]]
    keep = set(sub_nrm) | set(sub_abn)
    sub_df = sub_df[sub_df["traceID"].isin(keep)]
    t0 = time.perf_counter()
    top_o, _ = NumpyRefBackend(cfg).rank_window(sub_df, sub_nrm, sub_abn)
    oracle_s = time.perf_counter() - t0
    sps = len(sub_df) / oracle_s
    log(
        f"numpy oracle on {len(sub_df)}-span subsample: {oracle_s:.2f}s "
        f"-> {sps:,.0f} spans/s"
    )
    return sps, sub_df, sub_nrm, sub_abn, top_o


def _run_batched(
    cfg, table, slo_vocab, baseline, n_batch, repeats, truth,
    case_dir, oracle_spans, kernel,
) -> int:
    """BASELINE.json config 4 shape: an n_batch-window faulted timeline,
    each window detected/partitioned on the host and ALL of them ranked
    in ONE vmapped device program (`rank_windows_batched`)."""
    import jax
    import numpy as np

    host_start = _host_sentinel().sample()

    from microrank_tpu.graph.build import aux_for_kernel
    from microrank_tpu.graph.table_ops import build_window_graph_from_table
    from microrank_tpu.parallel import stack_window_graphs

    w_us = int(truth["window_minutes"] * 60e6)
    start = int(truth["start_us"])
    edges = [start + b * w_us for b in range(n_batch + 1)]

    from microrank_tpu.detect.detector import _thresholds
    from microrank_tpu.graph.table_ops import detect_window_partition

    thresh = _thresholds(baseline, cfg.detector)
    remap = slo_vocab.encode(table.svc_op_names).astype(np.int32)

    def detect_window(b):
        m, nrm, abn, _, rng = detect_window_partition(
            table, edges[b], edges[b + 1], slo_vocab, baseline,
            cfg.detector, remap=remap, thresh=thresh, with_range=True,
        )
        return m, nrm, abn, rng

    def build_all():
        graphs, names, total = [], list(table.pod_op_names), 0
        for b in range(n_batch):
            m, nrm, abn, rng = detect_window(b)
            if not (len(nrm) and len(abn)):
                continue
            g, _, _, _ = build_window_graph_from_table(
                table, m, nrm, abn, aux=aux_for_kernel(kernel),
                collapse=_collapse_mode(), row_range=rng,
            )
            graphs.append(g)
            total += int(m.sum())
        if not graphs:
            log("FATAL: no sub-window partitioned; tune the generator")
            raise SystemExit(1)
        return stack_window_graphs(graphs), names, total, len(graphs)

    stacked, op_names, spans_used, n_windows = build_all()
    from microrank_tpu.rank_backends.jax_tpu import choose_kernel as _choose

    resolved = (
        kernel if kernel != "auto"
        else _choose(stacked, prefer_bf16=_prefer_bf16())
    )
    log(f"batched mode: {n_windows}/{n_batch} sub-windows partitioned, "
        f"{spans_used} spans; kernel={resolved}")

    # Stage ONCE outside the timed loop — the same pipeline boundary the
    # single-window mode times at — so the two modes' numbers are
    # methodologically comparable. Staging is timed and in the headline
    # by default; BENCH_TIME_STAGING=0 excludes it.
    handle, _, stage_s = _stage_once(stacked, resolved)

    def run_fetched():
        return jax.device_get(
            _rank_batched_call(handle, cfg.pagerank, cfg.spectrum, resolved)
        )

    t0 = time.perf_counter()
    out = run_fetched()
    first_s = time.perf_counter() - t0
    log(f"first call (compile + run + fetch): {first_s:.2f}s")
    rank_times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = run_fetched()
        rank_times.append(time.perf_counter() - t0)
    rank_s = float(np.median(rank_times))  # repeats as configured, not
    # the clamped _time_median loop — this is the headline number
    build_times = []
    for _ in range(max(1, min(repeats, 3))):
        t0 = time.perf_counter()
        build_all()
        build_times.append(time.perf_counter() - t0)
    build_s = float(np.median(build_times))
    total_s = build_s + rank_s
    if _time_staging():
        total_s += stage_s
    sps = spans_used / total_s
    ti, ts, nv = out
    from microrank_tpu.evaluation import topk_exact

    # Tie-aware top-1 (the shared evaluation helper): an exact score
    # tie at rank 1 containing the fault still counts as a hit.
    hits = sum(
        topk_exact(
            [op_names[int(i)] for i in ti[b][: int(nv[b])]],
            [float(s) for s in ts[b][: int(nv[b])]],
            [truth["fault_pod_op"]],
            k=1,
        )
        for b in range(n_windows)
    )
    log(
        f"batched device path: build {build_s * 1e3:.0f}ms + one vmapped "
        f"rank {rank_s * 1e3:.0f}ms (+ staging {stage_s * 1e3:.0f}ms"
        f"{' timed' if _time_staging() else ''}) = {total_s * 1e3:.0f}ms -> "
        f"{sps:,.0f} spans/s; fault top-1 in {hits}/{n_windows} sub-windows"
    )

    # Oracle baseline on a trace subsample of sub-window 0.
    import pandas as pd

    sub_df = pd.read_csv(case_dir / "abnormal.csv")
    sub_df["startTime"] = pd.to_datetime(sub_df["startTime"])
    sub_df["endTime"] = pd.to_datetime(sub_df["endTime"])
    w0 = pd.Timestamp(np.datetime64(int(edges[0]), "us"))
    w1 = pd.Timestamp(np.datetime64(int(edges[1]), "us"))
    sub_df = sub_df[(sub_df["startTime"] >= w0) & (sub_df["endTime"] <= w1)]
    m0, nrm0, abn0, _ = detect_window(0)
    oracle_sps, _, _, _, _ = _oracle_subsample(
        cfg, sub_df, table.trace_names, nrm0, abn0, int(m0.sum()),
        oracle_spans,
    )

    print(
        json.dumps(
            {
                "metric": "spans_per_sec_ranked",
                "value": round(sps, 1),
                "unit": "spans/s",
                "vs_baseline": round(sps / oracle_sps, 2),
                "build_ms": round(build_s * 1e3, 1),
                "rank_ms": round(rank_s * 1e3, 1),
                "staging_ms": round(stage_s * 1e3, 1),
                "compile_ms": round(max(first_s - rank_s, 0.0) * 1e3, 1),
                **_host_fields(host_start, _host_sentinel().sample()),
            }
        )
    )
    return 0


def _run_replay(cfg, spans_per_window, n_ops, fault_ms, n_windows):
    """The pipelined-replay measurement (VERDICT r3 #3/#8): drive the
    REAL product path — TableRCA.run() with async dispatch and the
    depth-2 pipeline — over an n_windows faulted timeline and report
    aggregate ranked spans/s. Staging, detection, graph build, dispatch
    and fetch all count; their RPC latencies overlap across windows
    exactly as they do in production. First pass warms the jit caches
    (a real deployment ranks windows indefinitely; steady state is the
    honest number), second pass is timed.
    """
    import numpy as np

    from microrank_tpu.config import WindowConfig
    from microrank_tpu.graph.table_ops import window_rows
    from microrank_tpu.native import load_span_table
    from microrank_tpu.pipeline.table_runner import TableRCA

    case_dir, truth = _ensure_batch_data(
        spans_per_window * n_windows, n_ops, fault_ms, n_windows
    )
    normal_table = load_span_table(case_dir / "normal.csv")
    table = load_span_table(case_dir / "abnormal.csv")
    import dataclasses

    # Window arithmetic must visit each generated sub-window exactly:
    # detect = the generator's window span, skip = 0. fetch_mode="bulk"
    # is the replay-throughput configuration (one batched result fetch
    # instead of a ~110 ms RPC per window) — a first-class product mode
    # (`run --fetch-mode bulk`), not a bench special case. The replay
    # honors the same BENCH_KERNEL / BENCH_BLOB forcing as the
    # single-window phase, so a forced-kernel bench's headline measures
    # that kernel.
    cfg = cfg.replace(
        window=WindowConfig(
            detect_minutes=float(truth["window_minutes"]), skip_minutes=0.0
        ),
        runtime=dataclasses.replace(
            cfg.runtime,
            fetch_mode="bulk",
            kernel=os.environ.get("BENCH_KERNEL", "auto"),
            blob_staging=_use_blob(),
            # Group dispatches: one staging RPC per group instead of per
            # window (the replay is dispatch-RPC-bound once the host
            # work is O(window); `run --dispatch-batch-windows`).
            dispatch_batch_windows=int(
                os.environ.get("BENCH_DISPATCH_BATCH", 4)
            ),
        ),
        # Headline passes run spans-disabled; a second spans-enabled
        # measurement below reports the tracer's cost as the
        # ``trace_overhead`` artifact field (acceptance: within 5%).
        obs=dataclasses.replace(cfg.obs, spans=False),
    )
    # Compile witness (mrshape R13-R16's runtime mirror): armed with the
    # statically predicted key space for this config, every dispatch
    # seam reports its compile-key signature. The acceptance criterion
    # is zero keys outside the prediction — the artifact records it.
    from microrank_tpu.analysis import mrsan
    from microrank_tpu.analysis.shapes import predict_key_space

    mrsan.arm_witness(predict_key_space(cfg))
    rca = TableRCA(cfg)
    rca.fit_baseline(normal_table)
    host_start = _host_sentinel().sample()
    t0 = time.perf_counter()
    rca.run(table)
    warm_s = time.perf_counter() - t0
    # Median of 3 timed passes: the tunneled runtime's RPC latency
    # jitters ±20% run to run.
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        results = rca.run(table)
        times.append(time.perf_counter() - t0)
    replay_s = float(np.median(times))
    ranked = [r for r in results if r.ranking]
    spans_ranked = 0
    hits = 0
    for r in ranked:
        w0 = int(np.datetime64(r.start, "us").astype(np.int64))
        w1 = int(np.datetime64(r.end, "us").astype(np.int64))
        spans_ranked += int(window_rows(table, w0, w1).sum())
        hits += _fault_top1_hit(r.ranking, truth["fault_pod_op"])
    if not ranked:
        log("replay: no window ranked; skipping replay headline")
        return None
    sps = spans_ranked / replay_s
    log(
        f"pipelined replay: {len(ranked)}/{len(results)} windows ranked "
        f"({spans_ranked} spans) in {replay_s * 1e3:.0f}ms "
        f"(warmup+compile pass {warm_s:.2f}s) -> {sps:,.0f} spans/s "
        f"aggregate; fault top-1 in {hits}/{len(ranked)} windows; "
        f"{replay_s * 1e3 / len(ranked):.0f}ms/window"
    )
    # Tracer overhead: the SAME replay with the self-tracing span ring
    # armed (obs.spans) — every window emits its detect/dispatch/fetch
    # spans into the bounded ring. The artifact records both rates; the
    # acceptance bound is spans-on within 5% of spans-off.
    trace_overhead = None
    if os.environ.get("BENCH_TRACE_OVERHEAD", "1") != "0":
        from microrank_tpu.obs import get_tracer

        cfg_on = cfg.replace(obs=dataclasses.replace(cfg.obs, spans=True))
        rca_on = TableRCA(cfg_on)
        rca_on.fit_baseline(normal_table)
        times_on = []
        for _ in range(3):
            t0 = time.perf_counter()
            rca_on.run(table)
            times_on.append(time.perf_counter() - t0)
        sps_on = spans_ranked / float(np.median(times_on))
        trace_overhead = {
            "spans_per_sec_on": round(sps_on, 1),
            "spans_per_sec_off": round(sps, 1),
            "overhead_pct": round((1.0 - sps_on / sps) * 100.0, 2),
            "ring_spans": len(get_tracer()),
        }
        log(
            f"trace overhead: spans-on {sps_on:,.0f} vs spans-off "
            f"{sps:,.0f} spans/s "
            f"({trace_overhead['overhead_pct']:+.2f}%)"
        )
    from microrank_tpu.obs.metrics import snapshot_to_result_fields

    # One more (untimed) pass with an output dir when asked: produces
    # the run journal + metrics snapshot for this exact workload and
    # reconciles its per-window telemetry against the replay headline
    # (BENCH_JOURNAL_DIR=path; kept off the timed passes so journaling
    # cannot skew the number it documents).
    journal_fields = {}
    jdir = os.environ.get("BENCH_JOURNAL_DIR")
    if jdir:
        from microrank_tpu.obs import read_journal

        rca.run(table, out_dir=jdir)
        events = read_journal(Path(jdir) / "journal.jsonl")
        windows = [
            e for e in events
            if e["event"] == "window" and e.get("outcome") == "ranked"
        ]
        iters = [w.get("rank_iterations") for w in windows]

        def _rank_ms(w):
            # StageTimings keys are seconds; the *_ms fetch-amortization
            # keys are already milliseconds.
            ms = 0.0
            for k, v in (w.get("timings") or {}).items():
                if k.endswith("_ms"):
                    ms += v
                elif k.startswith("rank"):
                    ms += v * 1e3
            return ms

        rank_ms = [_rank_ms(w) for w in windows]
        journal_fields = {
            "journal_windows": len(windows),
            "journal_iterations_total": sum(i or 0 for i in iters),
            "journal_rank_ms_per_window": round(
                sum(rank_ms) / max(len(windows), 1), 1
            ),
            "journal_dir": jdir,
        }
        log(
            f"journal reconciliation: {len(windows)} ranked windows, "
            f"{sum(i or 0 for i in iters)} device iterations, "
            f"{journal_fields['journal_rank_ms_per_window']:.0f} "
            "rank-ms/window (vs replay "
            f"{replay_s * 1e3 / len(ranked):.0f} ms/window)"
        )

    witness = mrsan.witness_report()
    mrsan.disarm_witness()
    log(
        f"compile witness: {witness['keys_total']} key(s) observed, "
        f"{len(witness['unpredicted'])} outside the static prediction"
    )
    for esc in witness["unpredicted"]:
        log(f"compile witness ESCAPE: {esc['reason']}")

    return {
        **journal_fields,
        **(
            {"trace_overhead": trace_overhead} if trace_overhead else {}
        ),
        "replay_compile_keys": witness["keys_total"],
        "replay_compile_keys_by_program": witness["programs"],
        "replay_unpredicted_keys": len(witness["unpredicted"]),
        "replay_spans_per_sec": round(sps, 1),
        "replay_windows": len(ranked),
        "replay_ms": round(replay_s * 1e3, 1),
        "replay_ms_per_window": round(replay_s * 1e3 / len(ranked), 1),
        "replay_fault_hits": hits,
        # Telemetry accumulated by the replay's product path (the
        # TableRCA run records staging bytes + jit retraces): a retrace
        # count that grows with the window count is a compile storm.
        "replay_telemetry": snapshot_to_result_fields(),
        "replay_host": _host_fields(host_start, _host_sentinel().sample())[
            "host"
        ],
    }


def _run_router(cfg, spans_per_window, n_ops, fault_ms, n_windows):
    """Adaptive-router replay (PR 5): the same per-window graphs the
    batched mode builds, dispatched GROUP-wise through the shared
    DispatchRouter with double-buffered staging — group i+1's blob pack
    + H2D transfer overlaps group i's device execution, so staging_ms
    leaves the critical path. Produces the artifact's ``route`` /
    ``overlap_ms`` columns; ms/window here is the number to hold
    against BENCH_r05's 82 ms replay (where staging was additive)."""
    import numpy as np

    from microrank_tpu.detect.detector import _thresholds
    from microrank_tpu.dispatch import DispatchRouter
    from microrank_tpu.graph.build import aux_for_kernel
    from microrank_tpu.graph.table_ops import (
        build_window_graph_from_table,
        compute_slo_from_table,
        detect_window_partition,
    )
    from microrank_tpu.native import load_span_table
    from microrank_tpu.rank_backends.jax_tpu import (
        choose_kernel,
        device_subset,
    )

    case_dir, truth = _ensure_batch_data(
        spans_per_window * n_windows, n_ops, fault_ms, n_windows
    )
    normal = load_span_table(case_dir / "normal.csv")
    table = load_span_table(case_dir / "abnormal.csv")
    slo_vocab, baseline = compute_slo_from_table(normal)
    kernel = os.environ.get("BENCH_KERNEL", "auto")
    w_us = int(truth["window_minutes"] * 60e6)
    start = int(truth["start_us"])
    edges = [start + b * w_us for b in range(n_windows + 1)]
    thresh = _thresholds(baseline, cfg.detector)
    remap = slo_vocab.encode(table.svc_op_names).astype(np.int32)
    graphs, spans_used = [], 0
    for b in range(n_windows):
        m, nrm, abn, _, rng = detect_window_partition(
            table, edges[b], edges[b + 1], slo_vocab, baseline,
            cfg.detector, remap=remap, thresh=thresh, with_range=True,
        )
        if not (len(nrm) and len(abn)):
            continue
        g, _, _, _ = build_window_graph_from_table(
            table, m, nrm, abn, aux=aux_for_kernel(kernel),
            collapse=_collapse_mode(), row_range=rng,
        )
        graphs.append(g)
        spans_used += int(m.sum())
    if not graphs:
        log("router replay: no window partitioned; skipping")
        return None
    resolved = (
        kernel
        if kernel != "auto"
        else choose_kernel(graphs[0], prefer_bf16=_prefer_bf16())
    )
    graphs = [device_subset(g, resolved) for g in graphs]
    group_n = max(1, int(os.environ.get("BENCH_DISPATCH_BATCH", 4)))
    groups = [
        graphs[i : i + group_n] for i in range(0, len(graphs), group_n)
    ]
    router = DispatchRouter(cfg)

    def drive():
        infos = []
        for i, gr in enumerate(groups):
            nxt = (
                (groups[i + 1], resolved)
                if i + 1 < len(groups)
                else None
            )
            _, info = router.rank_batch(
                gr, resolved, next_batch=nxt, record=False
            )
            infos.append(info)
        return infos

    drive()  # warm pass: compiles every group occupancy outside the timer
    t0 = time.perf_counter()
    infos = drive()
    total_s = time.perf_counter() - t0
    overlap_ms = sum(i.overlap_ms for i in infos)
    routes = sorted({i.route for i in infos})
    ms_per_window = total_s * 1e3 / len(graphs)
    log(
        f"router replay: {len(graphs)} windows in {len(groups)} "
        f"group dispatches ({group_n}/group, route {routes}) in "
        f"{total_s * 1e3:.0f}ms -> {ms_per_window:.0f} ms/window; "
        f"{overlap_ms:.0f}ms of staging overlapped with rank"
    )
    return {
        "route": routes[0] if len(routes) == 1 else routes,
        "overlap_ms": round(overlap_ms, 1),
        "router": {
            "windows": len(graphs),
            "dispatches": len(groups),
            "group_windows": group_n,
            "kernel": resolved,
            "routes": routes,
            "ms_per_window": round(ms_per_window, 1),
            "overlap_ms": round(overlap_ms, 1),
            "spans_per_sec": round(spans_used / total_s, 1),
        },
    }


def _synthesize_giant_partition(rng, n_ops_v, n_traces, spans_per_trace):
    """Span-level int arrays for one giant partition: every trace draws
    ``spans_per_trace`` ops uniformly from the vocab (nearly every trace
    is a distinct kind, so trace-kind collapse CANNOT shrink this window
    — the whole point is the raw trace axis), plus a small random call
    edge set."""
    import numpy as np

    spans = n_traces * spans_per_trace
    g_trace = np.repeat(
        np.arange(n_traces, dtype=np.int64), spans_per_trace
    )
    op_codes = rng.integers(0, n_ops_v, size=spans, dtype=np.int64)
    n_edges = n_ops_v * 4
    child = rng.integers(0, n_ops_v, size=n_edges, dtype=np.int64)
    parent = rng.integers(0, n_ops_v, size=n_edges, dtype=np.int64)
    return op_codes, g_trace, child, parent


def _run_giant(cfg, repeats: int) -> dict:
    """The 10M-span giant-window tier (ROADMAP item 2): a synthetic
    window whose per-trace bitmap blows the DEFAULT bitmap budget —
    packed/packed_blocked cannot even be built — so the memory-bounded
    fallback IS the path, and the artifact records the csr -> pcsr
    delta (per-kernel ms_per_iter via the trip-count-differencing
    profile, staged HBM footprints, tie-aware rank parity vs the
    float64 sparse oracle on the full window).

    Sizes via env: BENCH_GIANT_SPANS (default 10_485_760),
    BENCH_GIANT_OPS (2048). No CSV/pandas anywhere — the case is about
    kernel time, so the span-level int arrays feed the real graph build
    (graph.build._build_partition) directly.
    """
    import dataclasses as _dc

    import jax
    import numpy as np

    from microrank_tpu.graph.build import (
        DEFAULT_DENSE_BUDGET_BYTES,
        _build_partition,
        packed_bits_bytes,
        resolve_aux,
    )
    from microrank_tpu.graph.structures import WindowGraph, pad_to
    from microrank_tpu.rank_backends.jax_tpu import (
        device_subset,
        graph_device_bytes,
    )
    from microrank_tpu.rank_backends.sparse_oracle import rank_window_sparse

    spans_target = int(os.environ.get("BENCH_GIANT_SPANS", 10_485_760))
    n_ops_v = int(os.environ.get("BENCH_GIANT_OPS", 2048))
    spans_per_trace = 4
    n_traces = spans_target // (2 * spans_per_trace)  # per partition
    v_pad = pad_to(n_ops_v, "pow2q", 8)
    rng = np.random.default_rng(12)

    t0 = time.perf_counter()
    parts = []
    for _ in range(2):
        op_codes, g_trace, child, parent = _synthesize_giant_partition(
            rng, n_ops_v, n_traces, spans_per_trace
        )
        # aux="all" builds every view family once; each kernel's staging
        # strips to what it reads (device_subset), so footprints stay
        # honest. The POLICY decision is asserted below instead.
        part, _ = _build_partition(
            op_codes, g_trace, child, parent, n_ops_v, v_pad,
            "pow2q", 8, aux="all",
        )
        parts.append(part)
    graph = WindowGraph(normal=parts[0], abnormal=parts[1])
    t_pads = tuple(
        int(p.kind.shape[0]) for p in (graph.normal, graph.abnormal)
    )
    bits_bytes = packed_bits_bytes(v_pad, t_pads)
    assert (
        resolve_aux("auto", v_pad, t_pads, DEFAULT_DENSE_BUDGET_BYTES)
        == "pcsr"
    ), "giant case must sit past the bitmap budget; grow BENCH_GIANT_SPANS"
    entries = sum(
        int(p.n_inc) for p in (graph.normal, graph.abnormal)
    )
    log(
        f"giant window: {2 * n_traces * spans_per_trace} spans, "
        f"{entries} incidence entries, t_pads {t_pads}, would-be bitmap "
        f"{bits_bytes / 1e6:.0f} MB (budget quarter "
        f"{DEFAULT_DENSE_BUDGET_BYTES // 4 / 1e6:.0f} MB) — past the "
        f"bitmap budget; built in {time.perf_counter() - t0:.1f}s"
    )

    names = [f"op{i:05d}" for i in range(n_ops_v)]
    t0 = time.perf_counter()
    top_o, sc_o = rank_window_sparse(
        graph, names, cfg.pagerank, cfg.spectrum
    )
    oracle_s = time.perf_counter() - t0
    log(f"float64 sparse oracle on the giant window: {oracle_s:.1f}s")

    out = {
        "case": {
            "spans": 2 * n_traces * spans_per_trace,
            "entries": entries,
            "v_pad": v_pad,
            "t_pads": list(t_pads),
            "bitmap_bytes_would_be": bits_bytes,
            "past_bitmap_budget": True,
        },
        "oracle_s": round(oracle_s, 1),
        "kernels": {},
    }
    base_iters = 2
    for kernel in ("pcsr", "csr"):
        handle, n_bytes, stage_s = _stage_once(graph, kernel)

        def run_iters(n, h=handle, kern=kernel):
            return jax.device_get(
                _rank_call(
                    h,
                    _dc.replace(cfg.pagerank, iterations=n),
                    cfg.spectrum,
                    kern,
                )
            )

        # Full-iteration run once: tie-aware top-5 parity vs the oracle.
        t0 = time.perf_counter()
        ti, ts, nv = run_iters(cfg.pagerank.iterations)
        full_s = time.perf_counter() - t0
        n = int(nv)
        parity = _tie_aware_topk_parity(
            [names[int(i)] for i in np.asarray(ti)[:n]],
            [float(s) for s in np.asarray(ts)[:n]],
            top_o,
            sc_o,
            k=5,
        )
        log(
            f"[giant {kernel}] full {cfg.pagerank.iterations}-iter rank: "
            f"{full_s:.1f}s (compile incl.); top-5 tie-aware parity vs "
            f"oracle: {parity}"
        )
        t_lo = _time_median(lambda: run_iters(base_iters), repeats)
        prof = _profile_device_time(
            run_iters, base_iters, t_lo, graph, kernel, repeats,
            extra=int(os.environ.get("BENCH_GIANT_EXTRA", 4)),
        )
        out["kernels"][kernel] = {
            **prof,
            "ms_per_iter": round(prof["per_iter_us"] / 1e3, 3),
            "hbm_footprint_bytes": graph_device_bytes(
                device_subset(graph, kernel)
            ),
            "staged_bytes": n_bytes,
            "staging_s": round(stage_s, 2),
            "parity_top5_vs_oracle": parity,
        }
        del handle
    pc = out["kernels"]["pcsr"]["per_iter_us"]
    cs = out["kernels"]["csr"]["per_iter_us"]
    out["speedup_pcsr_vs_csr"] = round(cs / pc, 2) if pc else None
    log(
        f"giant-window csr->pcsr per-iter speedup: "
        f"{out['speedup_pcsr_vs_csr']}x "
        f"({cs:.0f} -> {pc:.0f} us/iter)"
    )
    return out


def _sliding_timeline(n_traces, n_ops, span_us, rng):
    """Synthetic span frame for the sliding-window case: traces spread
    uniformly across ``span_us`` with temporally compact bodies (2 s
    bands) so a 75% slide changes only the boundary traces, and every
    op name recurs throughout (the delta lane's frozen-vocab
    contract). Vectorized — no per-span Python loop."""
    import numpy as np
    import pandas as pd

    lens = rng.integers(3, 8, size=n_traces)
    total = int(lens.sum())
    starts = np.concatenate(([0], np.cumsum(lens)[:-1]))
    tr = np.repeat(np.arange(n_traces), lens)
    j = np.arange(total) - np.repeat(starts, lens)
    base = rng.integers(0, max(span_us - 2_000_000, 1), size=n_traces)
    offs = rng.integers(0, 2_000_000, size=total)
    # Per-trace time order without a Python sort loop: order by
    # (trace, offset), then offsets are monotone inside each segment.
    order = np.lexsort((offs, tr))
    offs = offs[order]
    t_us = np.repeat(base, lens) + offs
    svc = rng.integers(0, 8, size=total)
    op = rng.integers(0, n_ops, size=total)
    tid = np.char.add("tr", tr.astype("U12"))
    sid = np.char.add(
        np.char.add(tid, "_s"), j.astype("U8")
    )
    parent = np.where(j > 0, np.roll(sid, 1), "")
    svc_names = np.char.add("svc", svc.astype("U4"))
    return pd.DataFrame(
        {
            "traceID": tid,
            "spanID": sid,
            "ParentSpanId": parent,
            "serviceName": svc_names,
            "operationName": np.char.add("op", op.astype("U4")),
            "podName": np.char.add(svc_names, "-pod0"),
            "startTime": pd.to_datetime(t_us, unit="us"),
            "duration": rng.integers(1, 100, size=total),
        }
    )


def _run_delta(cfg, spans_per_window, n_windows):
    """Incremental ranking economics (ISSUE 20 tentpole): the SAME
    sliding 75%-overlap replay ranked through both lanes — the cold
    control (full ``build_window_graph`` rebuild + the separate traced
    program) and the delta lane (O(Δ) ``build_window_graph_delta`` +
    the fused pair program) — with tie-aware top-5 parity required
    every window and exactly one fused dispatch per window certified
    by the dispatch counter + jit cache introspection. The amortized
    per-window build+device ratio is the acceptance number
    (``amortized_ratio`` <= 0.40 on the reference platform)."""
    import dataclasses as _dc

    import jax
    import numpy as np

    from microrank_tpu.dispatch import DispatchRouter
    from microrank_tpu.explain import ExplainContext
    from microrank_tpu.graph.build import (
        aux_for_kernel,
        build_window_graph,
        build_window_graph_delta,
    )
    from microrank_tpu.rank_backends.blob import stage_rank_window
    from microrank_tpu.rank_backends.jax_tpu import (
        choose_kernel,
        device_subset,
    )
    from microrank_tpu.rank_backends.warm import (
        capture_warm_state,
        map_warm_state,
    )

    w_us = 60_000_000
    s_us = 15_000_000               # 75% overlap
    span_us = w_us + (n_windows - 1) * s_us
    # Keep every op present in every window (a smoke-scale window that
    # misses one of the 48 ops would vocab-fallback the whole replay).
    n_ops = min(48, max(8, spans_per_window // 80))
    rng = np.random.default_rng(20)
    # ~5.5 spans/trace; scale the trace count so one window holds about
    # spans_per_window spans.
    n_traces = int(spans_per_window / 5.5 * span_us / w_us)
    df = _sliding_timeline(n_traces, n_ops, span_us, rng)
    t_all = df["startTime"].to_numpy().view("int64") // 1000

    def window(k):
        lo = k * s_us
        frame = df[(t_all >= lo) & (t_all < lo + w_us)]
        frame = frame.reset_index(drop=True)
        tids = sorted(frame["traceID"].unique())
        return frame, tids[::2], tids[1::2], lo, lo + w_us

    # Pin the pad buckets across the replay (the no-recompile guard
    # would otherwise rebuild cold whenever a padded count crossed a
    # bucket edge): floor the trace pad above the largest window, and
    # use full-doubling "pow2" buckets — the counts ABOVE the floor
    # (edge/incidence pads) fluctuate a few percent slide to slide,
    # which flaps pow2q's 25%-wide buckets but not pow2's. Both lanes
    # pay the identical padding, so the comparison stays fair.
    pad_policy = "pow2"
    frame0 = window(0)[0]
    # Per-PARTITION trace count (the windows split their traces in
    # half), with slack for slide-to-slide fluctuation.
    min_pad = 1 << int(
        np.ceil(np.log2(frame0["traceID"].nunique() / 2 * 1.25))
    )

    kernel = os.environ.get("BENCH_KERNEL", "auto")
    aux = aux_for_kernel(kernel) if kernel != "auto" else "auto"
    probe = build_window_graph(
        frame0, *window(0)[1:3], aux=aux, min_pad=min_pad,
        pad_policy=pad_policy,
    )
    if kernel == "auto":
        kernel = choose_kernel(probe[0], prefer_bf16=_prefer_bf16())
        if spans_per_window < 5_000 and kernel.endswith("_bf16"):
            # Smoke-scale scores are flat enough that the bf16 noise
            # floor (~1e-3 absolute) exceeds the parity rtol in
            # RELATIVE terms; the precision ladder is orthogonal to
            # what this case certifies, so converge in f32.
            kernel = kernel[: -len("_bf16")]
        aux = aux_for_kernel(kernel)

    # Both lanes rank TO CONVERGENCE (the reference's fixed 25 trips
    # are init-sensitive: a warm-started solve stopped at trip 25 sits
    # at a different point than a cold one, and the parity contract is
    # "tie-aware identical at convergence"). The tol also lets the
    # warm-threaded fused lane actually exit early.
    pr = _dc.replace(
        cfg.pagerank,
        tol=float(os.environ.get("BENCH_DELTA_TOL", 1e-4)),
        iterations=100,
    )
    cfg = cfg.replace(pagerank=pr)
    router = DispatchRouter(cfg)

    # --- cold control lane --------------------------------------------
    cold_build, cold_rank, cold_rankings = [], [], []
    for k in range(n_windows):
        frame, nrm, abn, lo, hi = window(k)
        t0 = time.perf_counter()
        g, names, _, _ = build_window_graph(
            frame, nrm, abn, aux=aux, min_pad=min_pad,
            pad_policy=pad_policy,
        )
        b_s = time.perf_counter() - t0
        gsub = device_subset(g, kernel)
        t0 = time.perf_counter()
        out = jax.device_get(
            stage_rank_window(gsub, pr, cfg.spectrum, kernel, _use_blob())
        )
        r_s = time.perf_counter() - t0
        if k:  # window 0 pays the compile for both lanes — excluded
            cold_build.append(b_s)
            cold_rank.append(r_s)
        nv = int(out[2])
        cold_rankings.append(
            (
                [names[int(i)] for i in np.asarray(out[0])[:nv]],
                [float(s) for s in np.asarray(out[1])[:nv]],
            )
        )

    # --- delta lane: incremental build + fused pair program -----------
    delta_build, delta_rank = [], []
    delta_route_build = []
    routes, parity = [], []
    state, warm = None, None
    cache_after_warmup = None
    extra_compiles = 0
    d0 = router.dispatches
    for k in range(n_windows):
        frame, nrm, abn, lo, hi = window(k)
        t0 = time.perf_counter()
        res = build_window_graph_delta(
            frame, nrm, abn, state=state, start_us=lo, end_us=hi,
            aux=aux, min_pad=min_pad, pad_policy=pad_policy,
        )
        b_s = time.perf_counter() - t0
        state = res.state
        routes.append(res.route if res.route == "delta" else res.reason)
        ectx = ExplainContext.from_build(
            res.graph, res.normal_trace_ids, res.abnormal_trace_ids,
            res.column_map[0], res.column_map[1],
        )
        init = (
            map_warm_state(warm, res.op_names, ectx, res.graph)
            if warm is not None
            else None
        )
        gsub = device_subset(res.graph, kernel)
        before = router.dispatches
        t0 = time.perf_counter()
        outs, info = router.rank_fused(gsub, kernel, init)
        r_s = time.perf_counter() - t0
        assert router.dispatches - before == 1, (
            "fused pair program must be ONE dispatch per window"
        )
        warm = capture_warm_state(res.op_names, ectx, outs[5:9])
        fused_fn = router_fused_cache_size()
        if k > 1 and res.route == "delta" and init is not None:
            # The no-recompile guarantee belongs to the DELTA route: a
            # delta graph carries the previous window's leaf-shape
            # signature by construction, so a warm fused dispatch past
            # the two warmup structures (cold seed init=None at k=0,
            # warm init=tuple at k=1) must never grow the jit cache. A
            # cold fallback MAY legitimately compile — its rebuilt pads
            # are whatever the new window needs.
            if cache_after_warmup is not None and (
                fused_fn != cache_after_warmup
            ):
                extra_compiles += fused_fn - cache_after_warmup
        cache_after_warmup = fused_fn
        if k:
            delta_build.append(b_s)
            delta_rank.append(r_s)
            if res.route == "delta":
                delta_route_build.append(b_s)
        nv = int(outs[2])
        names_d = [
            res.op_names[int(i)] for i in np.asarray(outs[0])[:nv]
        ]
        scores_d = [float(s) for s in np.asarray(outs[1])[:nv]]
        # rtol sits at the bf16 noise floor: under a *_bf16 kernel the
        # tol plateaus above BENCH_DELTA_TOL, so warm and cold
        # trajectories stop ~1e-3 apart in score while agreeing on the
        # ranking — exactly the tie-aware contract.
        parity.append(
            _tie_aware_topk_parity(
                names_d, scores_d, *cold_rankings[k], k=5, rtol=1e-2
            )
        )

    n_delta = sum(1 for r in routes if r == "delta")
    cold_ms = float(np.mean(cold_build) + np.mean(cold_rank)) * 1e3
    delta_ms = float(np.mean(delta_build) + np.mean(delta_rank)) * 1e3
    ratio = delta_ms / cold_ms if cold_ms else None
    out = {
        "windows": n_windows,
        "spans_per_window": int(len(frame0)),
        "kernel": kernel,
        "routes": routes,
        "delta_route_windows": n_delta,
        "cold_build_ms": round(float(np.mean(cold_build)) * 1e3, 1),
        "cold_rank_ms": round(float(np.mean(cold_rank)) * 1e3, 1),
        "delta_build_ms": round(float(np.mean(delta_build)) * 1e3, 1),
        "delta_route_build_ms": round(
            float(np.mean(delta_route_build)) * 1e3, 1
        ),
        "fused_rank_ms": round(float(np.mean(delta_rank)) * 1e3, 1),
        "amortized_cold_ms": round(cold_ms, 1),
        "amortized_delta_ms": round(delta_ms, 1),
        "amortized_ratio": round(ratio, 3) if ratio else None,
        "budget_ratio": 0.40,
        "within_budget": bool(ratio is not None and ratio <= 0.40),
        "parity_top5_every_window": all(parity),
        "fused_dispatches_per_window": round(
            (router.dispatches - d0) / n_windows, 2
        ),
        "fused_compiles_after_warmup": extra_compiles,
    }
    assert all(parity), (
        f"delta lane diverged from cold control (per-window: {parity})"
    )
    assert n_delta >= n_windows // 2, (
        f"delta route on {n_delta}/{n_windows} windows — the sliding "
        f"replay should take it on at least half (routes: {routes})"
    )
    assert extra_compiles == 0, (
        "fused pair program retraced on a delta-route window after warmup"
    )
    # The combined build+device ratio is rank-bound on a CPU smoke run
    # (both lanes pay the same per-iteration device cost and solve to
    # the same tol); the platform-robust invariant is the host build
    # itself: an incremental (delta-route) build must beat the full
    # rebuild. Cold-fallback windows inside the delta lane pay a full
    # rebuild by design, so they stay in the amortized mean but out of
    # this apples-to-apples comparison. Below smoke scale the delta
    # lane's fixed per-window cost (state capture + splice setup)
    # dominates a few-ms cold rebuild, so the numbers are recorded but
    # the O(Δ) win is only asserted where Δ-vs-window asymptotics
    # actually apply.
    if spans_per_window >= 5_000:
        assert out["delta_route_build_ms"] < out["cold_build_ms"], (
            f"delta-route build ({out['delta_route_build_ms']}ms) must "
            f"beat the cold rebuild ({out['cold_build_ms']}ms)"
        )
    log(
        f"delta replay: {n_delta}/{n_windows} windows on the delta "
        f"route; amortized build+device {delta_ms:.1f}ms vs cold "
        f"{cold_ms:.1f}ms ({ratio:.2f}x, budget 0.40); parity every "
        f"window; {out['fused_dispatches_per_window']} dispatches/window"
    )
    return out


def router_fused_cache_size():
    """Compiled-program count of the fused pair entry points (tree +
    blob twins) — flat after warmup is the no-retrace certificate the
    delta artifact records."""
    from microrank_tpu.rank_backends.blob import (
        rank_window_warm_blob_device,
    )
    from microrank_tpu.rank_backends.jax_tpu import (
        rank_window_warm_device,
    )

    total = 0
    for fn in (rank_window_warm_device, rank_window_warm_blob_device):
        size_fn = getattr(fn, "_cache_size", None)
        if size_fn is not None:
            try:
                total += int(size_fn())
            except Exception:
                pass
    return total


def _run_warehouse(cfg, spans_per_window, n_ops, fault_ms, n_windows):
    """Warehouse at-rest economics (ISSUE 18 satellite): the SAME
    multi-window case the pipelined replay drives, archived as warm
    columnar segments (kind-dictionary codes + delta ints via
    savez_compressed), then loaded back. The artifact records at-rest
    bytes vs the source CSV (acceptance: >=10x smaller) and segment
    load_ms vs CSV parse_ms (replay = blob load + dispatch, not
    parse)."""
    import numpy as np
    import pandas as pd

    from microrank_tpu.io import load_traces_csv
    from microrank_tpu.warehouse import load_warehouse_frame, write_segment
    from microrank_tpu.warehouse.segment import encode_window

    case_dir, _truth = _ensure_batch_data(
        spans_per_window * n_windows, n_ops, fault_ms, n_windows
    )
    csv_path = case_dir / "abnormal.csv"
    csv_bytes = csv_path.stat().st_size

    t0 = time.perf_counter()
    df = load_traces_csv(csv_path)
    parse_s = time.perf_counter() - t0

    # Archive as warm per-window segments, split on the generator's
    # window boundaries (the exact shape the stream engine seals).
    start = df["startTime"].min()
    width = pd.Timedelta(minutes=float(_truth["window_minutes"]))
    whdir = case_dir / "warehouse_bench"
    if whdir.exists():
        for f in whdir.glob("*.npz"):
            f.unlink()
    whdir.mkdir(exist_ok=True)
    at_rest = 0
    n_segments = 0
    for i in range(n_windows):
        w0, w1 = start + i * width, start + (i + 1) * width
        frame = df[(df["startTime"] >= w0) & (df["startTime"] < w1)]
        if frame.empty:
            continue
        us0 = int(w0.value // 1000)
        us1 = int(w1.value // 1000)
        rec = {
            "meta": {
                "start": str(w0), "end": str(w1),
                "start_us": us0, "end_us": us1,
                "outcome": "clean", "spans": int(len(frame)),
            },
            "frame": frame,
        }
        at_rest += write_segment(
            whdir / f"seg-{us0}-{us1}.npz", [encode_window(rec)]
        )
        n_segments += 1

    t0 = time.perf_counter()
    df2 = load_warehouse_frame(whdir)
    load_s = time.perf_counter() - t0
    assert len(df2) == int(
        ((df["startTime"] >= start)
         & (df["startTime"] < start + n_windows * width)).sum()
    ), "warehouse round-trip dropped rows"

    out = {
        "windows": n_segments,
        "rows": int(len(df2)),
        "csv_bytes": int(csv_bytes),
        "at_rest_bytes": int(at_rest),
        "compression_x": round(csv_bytes / at_rest, 2) if at_rest else None,
        "parse_ms": round(parse_s * 1e3, 1),
        "load_ms": round(load_s * 1e3, 1),
        "load_speedup_x": round(parse_s / load_s, 2) if load_s else None,
    }
    assert out["load_speedup_x"] and out["load_speedup_x"] > 1.0, (
        f"warehouse segment load ({out['load_ms']}ms) must beat the CSV "
        f"parse ({out['parse_ms']}ms); got {out['load_speedup_x']}x — "
        "the vectorized dictionary decode regressed"
    )
    log(
        f"warehouse: {n_segments} warm segments, at-rest "
        f"{at_rest / 1e6:.2f}MB vs CSV {csv_bytes / 1e6:.2f}MB "
        f"({out['compression_x']}x smaller); load {out['load_ms']}ms "
        f"vs parse {out['parse_ms']}ms ({out['load_speedup_x']}x)"
    )
    return out


def _run_sched(cfg, repeats):
    """Closed-loop saturation of the unified multi-tenant device
    scheduler (ISSUE 19): mixed tenants share ONE device through the
    ParkedWindowStore + DeviceScheduler. Two measurements:

    * fair share — three tenants on the backfill lane with weights
      1/2/4, each keeping BENCH_SCHED_OUTSTANDING windows in flight
      (closed loop: resubmit on completion), so the store always holds
      a backlog and the stride scheduler's dequeue order — not arrival
      order — decides who runs. Observed share must track weights.
    * lane latency — an interactive tenant (serve lane) submitting
      serially against that saturated backfill: its p50/p95/p99 shows
      what lane priority buys when the device is contended.

    Columns per tenant: windows, throughput (windows/s), p50/p95/p99
    latency ms, observed vs configured share."""
    import threading

    import numpy as np

    from microrank_tpu.config import (
        DetectorConfig,
        MicroRankConfig,
        SchedConfig,
        ServeConfig,
    )
    from microrank_tpu.detect import compute_slo, detect_numpy
    from microrank_tpu.dispatch.router import DispatchRouter
    from microrank_tpu.graph import build_detect_batch
    from microrank_tpu.rank_backends.jax_tpu import prepare_window_graph
    from microrank_tpu.sched import (
        DeviceScheduler,
        LANE_BACKFILL,
        LANE_SERVE,
        ParkedWindowStore,
    )
    from microrank_tpu.testing import SyntheticConfig, generate_case

    duration_s = float(os.environ.get("BENCH_SCHED_SECONDS", 4.0))
    outstanding = int(os.environ.get("BENCH_SCHED_OUTSTANDING", 8))
    weights = {"bronze": 1.0, "silver": 2.0, "gold": 4.0}

    case = generate_case(
        SyntheticConfig(n_operations=48, n_traces=200, seed=3)
    )
    vocab, baseline = compute_slo(case.normal)
    batch, trace_ids = build_detect_batch(case.abnormal, vocab)
    res = detect_numpy(batch, baseline, DetectorConfig())
    abn = [t for t, a in zip(trace_ids, res.abnormal) if a]
    nrm = [
        t
        for t, a, v in zip(trace_ids, res.abnormal, res.valid)
        if v and not a
    ]
    run_cfg = MicroRankConfig(
        sched=SchedConfig(
            tenant_weights=tuple(weights.items()),
        )
    )
    graph, _names, kernel = prepare_window_graph(
        case.abnormal, nrm, abn, run_cfg
    )
    router = DispatchRouter(run_cfg)

    def rank_once():
        outs, _ = router.rank_batch([graph], kernel)
        return outs

    rank_once()  # compile untimed, before the scheduler owns the device

    store = ParkedWindowStore(run_cfg.sched, serve_cfg=ServeConfig())
    sched = DeviceScheduler(store, name="mr-bench-sched")

    # Fair-share ordering probe: submit a standing backlog (30 windows
    # per tenant, round-robin arrival) BEFORE the scheduler thread
    # starts, so the stride scheduler — not arrival order — decides the
    # drain order. A closed loop can't show shares (work-conserving:
    # equal offered load completes equally); the dispatch ORDER under
    # backlog is where configured weights must appear.
    probe_order = []
    per_tenant_probe = 30

    def probe(tenant):
        rank_once()
        probe_order.append(tenant)  # scheduler thread: dispatch order

    probe_futs = [
        sched.submit_thunk(
            LANE_BACKFILL, t, lambda t=t: probe(t)
        )
        for _ in range(per_tenant_probe)
        for t in weights
    ]
    sched.start()
    try:
        for f in probe_futs:
            f.result(timeout=300)
        probe_share = {}
        n_prefix = len(probe_order) // 3
        for t, w in weights.items():
            probe_share[t] = probe_order[:n_prefix].count(t) / n_prefix

        lat = {t: [] for t in weights}
        lat["interactive"] = []
        stop_at = time.perf_counter() + duration_s
        lock = threading.Lock()

        def closed_loop(tenant, lane):
            while True:
                t0 = time.perf_counter()
                if t0 >= stop_at:
                    return
                sched.run_on(lane, tenant, rank_once)
                dt = time.perf_counter() - t0
                with lock:
                    lat[tenant].append(dt)

        threads = [
            threading.Thread(
                target=closed_loop, args=(t, LANE_BACKFILL),
                name=f"bench-{t}-{i}", daemon=True,
            )
            for t in weights
            for i in range(outstanding)
        ]
        threads.append(
            threading.Thread(
                target=closed_loop, args=("interactive", LANE_SERVE),
                name="bench-interactive", daemon=True,
            )
        )
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t_start
    finally:
        sched.stop(drain=True, timeout=30)

    total_w = sum(weights.values())
    tenants = {}
    for tenant in list(weights) + ["interactive"]:
        ts = sorted(lat[tenant])
        if not ts:
            continue
        arr = np.asarray(ts)
        tenants[tenant] = {
            "windows": len(ts),
            "throughput_wps": round(len(ts) / elapsed, 1),
            "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 1),
            "p95_ms": round(float(np.percentile(arr, 95)) * 1e3, 1),
            "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 1),
            **(
                {
                    "weight": weights[tenant],
                    "share_observed": round(probe_share[tenant], 3),
                    "share_configured": round(
                        weights[tenant] / total_w, 3
                    ),
                }
                if tenant in weights
                else {"lane": "serve"}
            ),
        }
    out = {
        "duration_s": round(elapsed, 2),
        "total_windows": int(sum(len(v) for v in lat.values())),
        "throughput_wps": round(
            sum(len(v) for v in lat.values()) / elapsed, 1
        ),
        "outstanding_per_tenant": outstanding,
        "kernel": kernel,
        "expired": store.expired,
        "tenants": tenants,
    }
    for t, row in tenants.items():
        log(
            f"sched[{t}]: {row['windows']} windows "
            f"({row['throughput_wps']}/s), p50 {row['p50_ms']}ms "
            f"p95 {row['p95_ms']}ms p99 {row['p99_ms']}ms"
            + (
                f", share {row['share_observed']:.3f} "
                f"(configured {row['share_configured']:.3f})"
                if "share_observed" in row
                else " [serve lane]"
            )
        )
    return out


def main() -> int:
    config_key = os.environ.get("BENCH_CONFIG", "5")
    preset = CONFIG_PRESETS.get(config_key)
    if preset is None:
        log(
            f"unknown BENCH_CONFIG={config_key!r} "
            f"(valid: {sorted(CONFIG_PRESETS)}); using config 5"
        )
        preset = CONFIG_PRESETS["5"]
    spans_target = int(os.environ.get("BENCH_SPANS", preset["spans"]))
    n_ops = int(os.environ.get("BENCH_OPS", preset["ops"]))
    repeats = int(os.environ.get("BENCH_REPEATS", 5))
    oracle_spans = int(os.environ.get("BENCH_ORACLE_SPANS", 20_000))
    fault_ms = float(os.environ.get("BENCH_FAULT_MS", 60_000.0))
    n_batch = int(os.environ.get("BENCH_BATCH", preset.get("batch", 1)))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from microrank_tpu.config import MicroRankConfig
    from microrank_tpu.graph.table_ops import (
        build_window_graph_from_table,
        compute_slo_from_table,
    )
    from microrank_tpu.native import load_span_table, native_available
    from microrank_tpu.rank_backends.jax_tpu import JaxBackend, choose_kernel

    _enable_compile_cache()
    host_start = _host_sentinel().sample()
    log(f"devices: {jax.devices()}; host load {host_start['norm_load']}")
    if not native_available():
        log("FATAL: native span loader unavailable (g++ missing?)")
        return 1
    cfg = MicroRankConfig()
    if n_batch > 1:
        case_dir, truth = _ensure_batch_data(
            spans_target, n_ops, fault_ms, n_batch
        )
    else:
        case_dir, truth = _ensure_data(spans_target, n_ops, fault_ms)

    # --- ingest (native lane) ------------------------------------------
    t0 = time.perf_counter()
    normal_table = load_span_table(case_dir / "normal.csv")
    abnormal_table = load_span_table(case_dir / "abnormal.csv")
    ingest_s = time.perf_counter() - t0
    n_spans = abnormal_table.n_spans
    log(
        f"native ingest: {ingest_s:.2f}s for "
        f"{normal_table.n_spans + n_spans} spans"
    )

    # --- detect + partition (host) -------------------------------------
    t0 = time.perf_counter()
    slo_vocab, baseline = compute_slo_from_table(normal_table)
    if n_batch > 1:  # per-window detection happens inside _run_batched
        return _run_batched(
            cfg, abnormal_table, slo_vocab, baseline, n_batch, repeats,
            truth, case_dir, oracle_spans,
            os.environ.get("BENCH_KERNEL", "auto"),
        )
    # The shared detection seam (fused C++ scan; same path TableRCA
    # runs, with its own numpy fallback inside).
    from microrank_tpu.graph.table_ops import detect_window_partition

    mask, nrm, abn, _ = detect_window_partition(
        abnormal_table,
        int(abnormal_table.start_us.min()),
        int(abnormal_table.end_us.max()),
        slo_vocab,
        baseline,
        cfg.detector,
    )
    detect_s = time.perf_counter() - t0
    log(
        f"detect+partition: {detect_s:.2f}s "
        f"({len(nrm)} normal / {len(abn)} abnormal traces)"
    )
    if not (len(nrm) and len(abn)):
        log("FATAL: window did not partition; tune the generator")
        return 1

    # --- timed device path: graph build (host) + rank (device) ---------
    from microrank_tpu.graph.build import aux_for_kernel

    kernel = os.environ.get("BENCH_KERNEL", "auto")

    def build():
        return build_window_graph_from_table(
            abnormal_table, mask, nrm, abn, aux=aux_for_kernel(kernel),
            collapse=_collapse_mode(),
        )

    graph, op_names, _, _ = build()
    if kernel == "auto":
        kernel = choose_kernel(graph, prefer_bf16=_prefer_bf16())
    collapsed = int(graph.normal.n_cols) >= 0
    from microrank_tpu.graph.build import kind_dedup_ratio

    kind_dedup = kind_dedup_ratio(graph)
    log(
        f"pagerank kernel: {kernel}"
        + (
            f"; kind-collapsed trace axes "
            f"{int(graph.normal.n_traces)}->{int(graph.normal.n_cols)} / "
            f"{int(graph.abnormal.n_traces)}->{int(graph.abnormal.n_cols)}"
            f" (dedup factor {kind_dedup:.1f}x)"
            if collapsed
            else ""
        )
    )

    # Host->device staging happens once per window in a real pipeline
    # (and overlaps the next window's host build there — jax dispatch is
    # async and the table pipeline runs pipeline_depth deep). It is part
    # of the headline by default (BENCH_TIME_STAGING=0 excludes it);
    # blob staging makes that honest inclusion affordable — one transfer
    # instead of ~50 per-leaf RPC round trips. device_subset (inside
    # _stage_once) drops the arrays the chosen kernel never reads.
    handle, n_bytes, stage_s = _stage_once(graph, kernel)

    # Timing note: on the tunneled TPU platform ("axon"),
    # jax.block_until_ready returns without waiting for device execution —
    # measured 0.1 ms for a program whose value-fetch takes 80 ms. The only
    # trustworthy fence is a device->host transfer of the outputs, so every
    # timed call below materializes the (tiny: top-k indices/scores) result
    # on the host — in ONE batched jax.device_get (per-buffer fetches each
    # pay a full RPC round trip, ~78 ms apiece measured). The transfer is
    # part of an honest end-to-end rank anyway — the ranking is consumed
    # host-side.
    def run_fetched():
        return jax.device_get(
            _rank_call(handle, cfg.pagerank, cfg.spectrum, kernel)
        )

    t0 = time.perf_counter()
    out = run_fetched()
    first_s = time.perf_counter() - t0
    log(f"first call (compile + run + fetch): {first_s:.2f}s")

    rank_times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = run_fetched()
        rank_times.append(time.perf_counter() - t0)
    rank_s = float(np.median(rank_times))

    build_times = []
    for _ in range(max(1, min(repeats, 3))):
        t0 = time.perf_counter()
        build()
        build_times.append(time.perf_counter() - t0)
    build_s = float(np.median(build_times))

    # --- explain overhead (rank provenance, ISSUE 8) --------------------
    # The headline above runs the PLAIN programs (explain off — the
    # default costs nothing by construction); this measures what the
    # explained twin costs when asked: the same window through
    # stage_rank_window with conv_trace vs with the explain epilogue
    # (attribution tensors riding the fetch). BENCH_EXPLAIN_OVERHEAD=0
    # skips.
    explain_overhead = None
    if os.environ.get("BENCH_EXPLAIN_OVERHEAD", "1") != "0":
        try:
            from microrank_tpu.config import ExplainConfig
            from microrank_tpu.rank_backends.blob import stage_rank_window
            from microrank_tpu.rank_backends.jax_tpu import device_subset

            ex_cfg = ExplainConfig(enabled=True)
            g_sub = device_subset(graph, kernel)

            def run_explained():
                return jax.device_get(
                    stage_rank_window(
                        g_sub, cfg.pagerank, cfg.spectrum, kernel,
                        _use_blob(), explain=ex_cfg,
                    )
                )

            def run_plain():
                return jax.device_get(
                    stage_rank_window(
                        g_sub, cfg.pagerank, cfg.spectrum, kernel,
                        _use_blob(), conv_trace=True,
                    )
                )

            run_explained()
            run_plain()  # both compiled before timing
            n_rep = max(3, min(repeats, 5))
            ms_on = _time_median(run_explained, n_rep) * 1e3
            ms_off = _time_median(run_plain, n_rep) * 1e3
            explain_overhead = {
                "ms_explained": round(ms_on, 1),
                "ms_plain": round(ms_off, 1),
                "overhead_pct": round((ms_on / ms_off - 1.0) * 100.0, 2),
                "kernel": kernel,
            }
            log(
                f"explain overhead: explained {ms_on:.0f}ms vs plain "
                f"{ms_off:.0f}ms per window "
                f"({explain_overhead['overhead_pct']:+.1f}%)"
            )
        except Exception as exc:  # diagnostics must not eat the metric
            log(f"explain overhead measurement failed ({exc!r}); continuing")

    # --- device-time isolation + utilization (VERDICT r2 #1) -----------
    # Differencing loop trip counts cancels the RPC floor; analytic
    # per-iteration traffic turns the slope into HBM/MXU utilization.
    # Profiles the resolved kernel AND (unless BENCH_DEVICE_PROFILE=0)
    # the csr family on the same window for the DESIGN.md comparison.
    import dataclasses as _dc

    device_profile = {}
    if (
        os.environ.get("BENCH_DEVICE_PROFILE", "1") != "0"
        and cfg.pagerank.tol is None  # differencing needs full trips
    ):
        def run_iters(n, h=handle, kern=kernel):
            return jax.device_get(
                _rank_call(
                    h,
                    _dc.replace(cfg.pagerank, iterations=n),
                    cfg.spectrum,
                    kern,
                )
            )

        try:
            if kernel in (
                "packed", "packed_bf16", "packed_blocked", "csr", "pcsr",
                "kind",
            ):
                device_profile[kernel] = _profile_device_time(
                    run_iters, cfg.pagerank.iterations, rank_s, graph,
                    kernel, repeats,
                )
            for other in (
                "kind", "pcsr", "csr", "packed_bf16", "packed_blocked",
            ):
                if other == kernel or other in device_profile:
                    continue
                # Forced aux builds ignore the budgets the auto policy
                # applies — skip kernels whose views/intermediates would
                # blow them rather than OOM a diagnostic.
                from microrank_tpu.graph.build import (
                    DEFAULT_DENSE_BUDGET_BYTES,
                    packed_unpacked_bytes,
                    resolve_aux,
                )

                v_pad = graph.normal.cov_unique.shape[-1]
                t_pads = (
                    graph.normal.kind.shape[-1],
                    graph.abnormal.kind.shape[-1],
                )
                unpacked = packed_unpacked_bytes(v_pad, t_pads)
                if (
                    other in ("packed", "packed_bf16")
                    and unpacked > DEFAULT_DENSE_BUDGET_BYTES
                ):
                    log(f"[{other}] skipped: past the dense budget")
                    continue
                if other == "packed_blocked" and resolve_aux(
                    "auto", v_pad, t_pads, DEFAULT_DENSE_BUDGET_BYTES
                ) != "packed":
                    log(f"[{other}] skipped: bitmaps past the budget")
                    continue
                g2, _, _, _ = build_window_graph_from_table(
                    abnormal_table, mask, nrm, abn,
                    aux=aux_for_kernel(other),
                    collapse=_collapse_mode(),
                )
                h2, _, _ = _stage_once(g2, other)

                def run2(n, h=h2, kern=other):
                    return run_iters(n, h, kern)

                t0 = time.perf_counter()
                run2(cfg.pagerank.iterations)
                log(
                    f"[{other}] first call: "
                    f"{time.perf_counter() - t0:.2f}s"
                )
                t_lo2 = _time_median(
                    lambda: run2(cfg.pagerank.iterations), repeats
                )
                device_profile[other] = _profile_device_time(
                    run2, cfg.pagerank.iterations, t_lo2, g2, other,
                    repeats,
                )
        except Exception as exc:  # diagnostics must not eat the metric
            log(f"device profiling failed ({exc!r}); continuing")

    total_s = build_s + rank_s
    if _time_staging():
        total_s += stage_s
    spans_per_sec = n_spans / total_s
    top_idx, top_scores, n_valid = out
    jax_top1 = op_names[int(np.asarray(top_idx)[0])]
    from microrank_tpu.evaluation import topk_exact

    n_live = int(n_valid)
    fault_hit = topk_exact(
        [op_names[int(i)] for i in np.asarray(top_idx)[:n_live]],
        [float(s) for s in np.asarray(top_scores)[:n_live]],
        [truth["fault_pod_op"]],
        k=1,
    )
    log(
        f"device path: build {build_s * 1e3:.0f}ms + rank {rank_s * 1e3:.0f}ms "
        f"(+ staging {stage_s * 1e3:.0f}ms"
        f"{' timed' if _time_staging() else ''})"
        f" = {total_s * 1e3:.0f}ms -> {spans_per_sec:,.0f} spans/s; "
        f"top-1 {jax_top1} (fault {truth['fault_pod_op']}, hit={fault_hit})"
    )

    # --- oracle baseline on a subsample (pandas lane, untimed load) ----
    import pandas as pd

    oracle_sps, sub_df, sub_nrm, sub_abn, top_o = _oracle_subsample(
        cfg,
        pd.read_csv(case_dir / "abnormal.csv"),
        abnormal_table.trace_names,
        nrm,
        abn,
        n_spans,
        oracle_spans,
    )

    top_j, _ = JaxBackend(cfg).rank_window(sub_df, sub_nrm, sub_abn)
    parity = top_o[0] == top_j[0]
    log(f"subsample Top-1 parity (oracle vs jax): {parity} ({top_o[0]})")

    # Full-window sparse-oracle parity (VERDICT r3 #5): rank the ACTUAL
    # 1M-span window with the float64 COO oracle (no dense [V, T]
    # matrices; seconds, not minutes) and require top-5 positional
    # agreement, tie-aware, against the device ranking.
    full_parity = None
    full_oracle_s = None
    if os.environ.get("BENCH_FULL_ORACLE", "1") != "0":
        from microrank_tpu.rank_backends.sparse_oracle import (
            rank_window_sparse,
        )

        # The oracle ranks an UNCOLLAPSED build of the same window — that
        # makes this check validate the kind-collapse end to end (device
        # on collapsed vs float64 per-trace semantics), not just the
        # kernel. aux="none": the oracle reads only the COO entries.
        oracle_graph = graph
        if collapsed:
            oracle_graph, _, _, _ = build_window_graph_from_table(
                abnormal_table, mask, nrm, abn, aux="none", collapse="off"
            )
        t0 = time.perf_counter()
        top_full_o, sc_full_o = rank_window_sparse(
            oracle_graph, op_names, cfg.pagerank, cfg.spectrum
        )
        full_oracle_s = time.perf_counter() - t0
        nv = int(n_valid)
        names_j = [op_names[int(i)] for i in np.asarray(top_idx)[:nv]]
        scores_j = [float(s) for s in np.asarray(top_scores)[:nv]]
        full_parity = _tie_aware_topk_parity(
            names_j, scores_j, top_full_o, sc_full_o, k=5
        )
        log(
            f"full-window sparse oracle: {full_oracle_s:.1f}s; top-5 "
            f"positional parity (tie-aware) vs jax: {full_parity} "
            f"(oracle top-1 {top_full_o[0]})"
        )

    # Kind-vs-packed per-iteration speedup — the ISSUE-14 acceptance
    # number (>2x on the 1M-span window), computed from the differenced
    # device profiles whenever both sides were measured.
    speedup_kind = None
    kind_prof = device_profile.get("kind")
    packed_prof = device_profile.get("packed_bf16") or device_profile.get(
        "packed"
    )
    if kind_prof and packed_prof and kind_prof["per_iter_us"]:
        speedup_kind = round(
            packed_prof["per_iter_us"] / kind_prof["per_iter_us"], 2
        )
        log(
            f"kind vs packed per-iter speedup: {speedup_kind}x "
            f"({packed_prof['per_iter_us']:.0f} -> "
            f"{kind_prof['per_iter_us']:.0f} us/iter)"
        )

    result = {
        "metric": "spans_per_sec_ranked",
        "value": round(spans_per_sec, 1),
        "unit": "spans/s",
        "vs_baseline": round(spans_per_sec / oracle_sps, 2),
        # Reduced-precision / kind-compression telemetry (ISSUE 14):
        # the window's measured dedup factor (the auto-select signal),
        # the kind matvec precision in effect, and the headline
        # kind-vs-packed per-iteration speedup when both profiled.
        "kind_dedup": round(kind_dedup, 2),
        "kind_precision": cfg.pagerank.kind_precision,
        **(
            {"speedup_kind_vs_packed": speedup_kind}
            if speedup_kind is not None
            else {}
        ),
        # One-time C++ mmap ingest of the whole dump (normal + abnormal
        # CSVs -> interned arrays; sidecar-cached across runs). Not part
        # of the per-window numbers: a deployment ingests a span once
        # and ranks it in every window it falls into.
        "ingest_ms": round(ingest_s * 1e3, 1),
        "build_ms": round(build_s * 1e3, 1),
        "rank_ms": round(rank_s * 1e3, 1),
        "staging_ms": round(stage_s * 1e3, 1),
        "compile_ms": round(max(first_s - rank_s, 0.0) * 1e3, 1),
        **(
            {
                "full_window_parity_top5": full_parity,
                "full_oracle_s": round(full_oracle_s, 2),
            }
            if full_parity is not None
            else {}
        ),
        **(
            {"explain_overhead": explain_overhead}
            if explain_overhead
            else {}
        ),
        **({"device": device_profile} if device_profile else {}),
        **_host_fields(host_start, _host_sentinel().sample()),
    }

    # Pipelined replay over a multi-window timeline: the aggregate
    # throughput of the real pipeline (async dispatch overlapping
    # staging/rank RPCs with the next window's host work) IS the
    # headline when the preset asks for it — per-window fixed RPC
    # latency is a tunnel artifact the production loop amortizes, and
    # the replay still counts every cost end to end.
    replay_n = int(os.environ.get("BENCH_REPLAY", preset.get("replay", 1)))
    if replay_n > 1:
        try:
            rep = _run_replay(cfg, spans_target, n_ops, fault_ms, replay_n)
        except Exception as exc:  # replay must not eat the single metric
            log(f"replay failed ({exc!r}); keeping single-window headline")
            rep = None
        if rep is not None:
            result.update(rep)
            result["single_window_spans_per_sec"] = result["value"]
            result["value"] = rep["replay_spans_per_sec"]
            result["vs_baseline"] = round(
                rep["replay_spans_per_sec"] / oracle_sps, 2
            )
        # Router-driven replay: route + overlap columns (double-buffered
        # staging overlapping rank — BENCH_ROUTER=0 skips).
        if os.environ.get("BENCH_ROUTER", "1") != "0":
            try:
                routed = _run_router(
                    cfg, spans_target, n_ops, fault_ms, replay_n
                )
            except Exception as exc:  # diagnostics must not eat the metric
                log(f"router replay failed ({exc!r}); continuing")
                routed = None
            if routed is not None:
                result.update(routed)

    # Incremental ranking (ISSUE 20): sliding 75%-overlap replay ranked
    # through the delta lane (O(Δ) build + fused pair program) against
    # a cold-control rebuild, tie-aware parity every window.
    # BENCH_DELTA=0 skips.
    if os.environ.get("BENCH_DELTA", "1") != "0":
        try:
            # Capped by the preset so smoke configs (BENCH_CONFIG=1)
            # pay a proportionally small sliding replay.
            delta_spans = int(
                os.environ.get(
                    "BENCH_DELTA_SPANS", min(20_000, spans_target)
                )
            )
            result["delta"] = _run_delta(
                cfg,
                delta_spans,
                int(os.environ.get("BENCH_DELTA_WINDOWS", 8)),
            )
        except Exception as exc:  # diagnostics must not eat the metric
            log(f"delta replay case failed ({exc!r}); continuing")

    # Warehouse at-rest economics (ISSUE 18): archive the replay case
    # as warm columnar segments and record bytes + load-vs-parse time.
    # BENCH_WAREHOUSE=0 skips.
    if os.environ.get("BENCH_WAREHOUSE", "1") != "0":
        try:
            result["warehouse"] = _run_warehouse(
                cfg, spans_target, n_ops, fault_ms, max(replay_n, 4)
            )
        except Exception as exc:  # diagnostics must not eat the metric
            log(f"warehouse case failed ({exc!r}); continuing")

    # Unified multi-tenant device scheduler (ISSUE 19): closed-loop
    # saturation under mixed tenants — fair-share convergence + what
    # lane priority buys the interactive tenant. BENCH_SCHED=0 skips.
    if os.environ.get("BENCH_SCHED", "1") != "0":
        try:
            result["sched"] = _run_sched(cfg, repeats)
        except Exception as exc:  # diagnostics must not eat the metric
            log(f"sched saturation case failed ({exc!r}); continuing")

    # Giant-window tier (ROADMAP item 2): a 10M-span synthetic window
    # past the DEFAULT bitmap budget — the memory-bounded fallback's
    # home turf — recording the csr -> pcsr per-iteration delta and
    # per-kernel staged footprints. BENCH_GIANT=0 skips.
    if os.environ.get("BENCH_GIANT", "1") != "0":
        try:
            result["giant"] = _run_giant(cfg, repeats)
        except Exception as exc:  # diagnostics must not eat the metric
            log(f"giant-window case failed ({exc!r}); continuing")

    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
