"""Benchmark harness: rank one large trace window on the device backend.

Prints ONE JSON line:
    {"metric": "spans_per_sec_ranked", "value": N, "unit": "spans/s",
     "vs_baseline": R}

* value — spans of the abnormal window ranked per second of wall-clock
  through the device path (host COO graph build + jitted rank program,
  post-compile; median of BENCH_REPEATS runs).
* vs_baseline — speedup of that spans/s over the faithful numpy oracle
  backend measured on a trace-subsample of the same window (the oracle is
  the reference's dense-matrix semantics; its cost is superlinear, so the
  subsample keeps the baseline measurable — the ratio therefore
  *understates* the real speedup at full scale).

Config via env: BENCH_SPANS (default 1_000_000), BENCH_OPS (5000),
BENCH_REPEATS (5), BENCH_ORACLE_SPANS (20_000). Details go to stderr;
stdout carries only the JSON line.

Reference baseline context: the reference's PageRank Scorer takes 5.5 s
per window of ~1e2 ops / 1e2-1e3 traces on a CPU core (paper Table 7;
BASELINE.md) — the target here is a window 3-4 orders of magnitude larger
in under a second (BASELINE.json north star).
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    spans_target = int(os.environ.get("BENCH_SPANS", 1_000_000))
    n_ops = int(os.environ.get("BENCH_OPS", 5000))
    repeats = int(os.environ.get("BENCH_REPEATS", 5))
    oracle_spans = int(os.environ.get("BENCH_ORACLE_SPANS", 20_000))
    # Expected-duration margins grow with trace depth (sum of inclusive
    # span SLOs), so the injected latency must scale with topology size.
    fault_ms = float(os.environ.get("BENCH_FAULT_MS", 60_000.0))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from microrank_tpu.config import MicroRankConfig
    from microrank_tpu.detect import compute_slo, detect_numpy
    from microrank_tpu.graph import build_detect_batch, build_window_graph
    from microrank_tpu.rank_backends import NumpyRefBackend
    from microrank_tpu.rank_backends.jax_tpu import (
        choose_kernel,
        rank_window_device,
    )
    from microrank_tpu.testing import SyntheticConfig, generate_case_with_spans

    log(f"devices: {jax.devices()}")
    cfg = MicroRankConfig()

    t0 = time.perf_counter()
    case = generate_case_with_spans(
        SyntheticConfig(
            n_operations=n_ops,
            n_kinds=max(32, n_ops // 50),
            child_keep_prob=0.55,
            fault_latency_ms=fault_ms,
            seed=0,
        ),
        target_spans=spans_target,
    )
    n_spans = len(case.abnormal)
    log(
        f"generated case in {time.perf_counter() - t0:.1f}s: "
        f"{n_spans} abnormal spans, {case.abnormal['traceID'].nunique()} traces, "
        f"{n_ops} operations"
    )

    # Detect + partition (host; not part of the timed rank path, matching
    # the reference's Table 7 which times the PageRank Scorer stage).
    t0 = time.perf_counter()
    vocab, baseline = compute_slo(case.normal)
    batch, trace_ids = build_detect_batch(case.abnormal, vocab)
    res = detect_numpy(batch, baseline, cfg.detector)
    trace_arr = np.asarray(trace_ids)
    abn = trace_arr[res.abnormal[: len(trace_arr)]].tolist()
    nrm_mask = res.valid[: len(trace_arr)] & ~res.abnormal[: len(trace_arr)]
    nrm = trace_arr[nrm_mask].tolist()
    detect_s = time.perf_counter() - t0
    log(
        f"detect+partition: {detect_s:.2f}s "
        f"({len(nrm)} normal / {len(abn)} abnormal traces)"
    )
    if not (nrm and abn):
        log("FATAL: window did not partition; tune the generator")
        return 1

    # --- timed device path: graph build (host) + rank (device) ---------
    def build():
        return build_window_graph(case.abnormal, nrm, abn)

    t0 = time.perf_counter()
    graph, op_names, _, _ = build()
    build_s = time.perf_counter() - t0
    log(f"graph build (host, cold): {build_s:.2f}s")

    kernel = os.environ.get("BENCH_KERNEL", "auto")
    if kernel == "auto":
        kernel = choose_kernel(graph, cfg.runtime.dense_budget_bytes)
    log(f"pagerank kernel: {kernel}")

    device_graph = jax.tree.map(jnp.asarray, graph)
    t0 = time.perf_counter()
    out = rank_window_device(
        device_graph, cfg.pagerank, cfg.spectrum, None, kernel
    )
    jax.block_until_ready(out)
    log(f"first call (compile + run): {time.perf_counter() - t0:.2f}s")

    rank_times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = rank_window_device(
            device_graph, cfg.pagerank, cfg.spectrum, None, kernel
        )
        jax.block_until_ready(out)
        rank_times.append(time.perf_counter() - t0)
    rank_s = float(np.median(rank_times))

    build_times = []
    for _ in range(max(1, min(repeats, 3))):
        t0 = time.perf_counter()
        build()
        build_times.append(time.perf_counter() - t0)
    build_s = float(np.median(build_times))

    total_s = build_s + rank_s
    spans_per_sec = n_spans / total_s
    top_idx, top_scores, n_valid = out
    jax_top1 = op_names[int(np.asarray(top_idx)[0])]
    log(
        f"device path: build {build_s * 1e3:.0f}ms + rank {rank_s * 1e3:.0f}ms "
        f"= {total_s * 1e3:.0f}ms -> {spans_per_sec:,.0f} spans/s; "
        f"top-1 {jax_top1} (fault {case.fault_pod_op})"
    )

    # --- oracle baseline on a subsample --------------------------------
    sub_traces = []
    count = 0
    per_trace = max(1, n_spans // max(len(trace_arr), 1))
    for t in nrm + abn:
        sub_traces.append(t)
        count += per_trace
        if count >= oracle_spans:
            break
    sub_set = set(sub_traces)
    sub_df = case.abnormal[case.abnormal["traceID"].isin(sub_set)]
    sub_nrm = [t for t in nrm if t in sub_set]
    sub_abn = [t for t in abn if t in sub_set]
    if not sub_abn:
        sub_abn = abn[:2]
        sub_df = case.abnormal[
            case.abnormal["traceID"].isin(sub_set | set(sub_abn))
        ]
    n_sub = len(sub_df)
    oracle = NumpyRefBackend(cfg)
    t0 = time.perf_counter()
    top_o, _ = oracle.rank_window(sub_df, sub_nrm, sub_abn)
    oracle_s = time.perf_counter() - t0
    oracle_sps = n_sub / oracle_s
    log(
        f"numpy oracle on {n_sub}-span subsample: {oracle_s:.2f}s "
        f"-> {oracle_sps:,.0f} spans/s"
    )

    # Parity on the subsample through the device backend.
    from microrank_tpu.rank_backends.jax_tpu import JaxBackend

    top_j, _ = JaxBackend(cfg).rank_window(sub_df, sub_nrm, sub_abn)
    parity = top_o[0] == top_j[0]
    log(f"subsample Top-1 parity (oracle vs jax): {parity} ({top_o[0]})")

    vs_baseline = spans_per_sec / oracle_sps
    print(
        json.dumps(
            {
                "metric": "spans_per_sec_ranked",
                "value": round(spans_per_sec, 1),
                "unit": "spans/s",
                "vs_baseline": round(vs_baseline, 2),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
